// accl_tpu native rank daemon: a C++ emulated device behind the framed-TCP
// protocol (accl_tpu/emulator/protocol.py).
//
// Role parity with the reference's CPU emulator process
// (test/emulation/cclo_emu.cpp): one OS process per rank hosting device
// memory, an eager-ingress spare-buffer pool with (src, tag, seqn) envelope
// matching (rxbuf_offload engines + seek_rx_buffer), a control plane that
// expands collectives into move micro-ops (ccl_offload_control.c:502-1098),
// and a dataplane executor (dma_mover + reduce_sum/compression plugins).
// The Python driver's SimDevice cannot tell this daemon from the Python one
// (accl_tpu/emulator/daemon.py) — the property the 3-tier test story needs.
//
// Build: make -C native   (g++ -O2 -std=c++17 -pthread)

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <set>

#include "bs_codec.h"
#include "protocol.hpp"

using namespace accl_proto;

static float half_to_float(uint16_t h) {
  uint32_t sign = (h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1F;
  uint32_t man = h & 0x3FF;
  uint32_t bits;
  if (exp == 0) {
    if (man == 0) {
      bits = sign;
    } else {  // subnormal: normalize
      int shift = 0;
      while (!(man & 0x400)) { man <<= 1; ++shift; }
      man &= 0x3FF;
      bits = sign | ((127 - 15 - shift) << 23) | (man << 13);
    }
  } else if (exp == 31) {
    bits = sign | 0x7F800000u | (man << 13);
  } else {
    bits = sign | ((exp - 15 + 127) << 23) | (man << 13);
  }
  float f;
  std::memcpy(&f, &bits, 4);
  return f;
}

static uint16_t float_to_half(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  uint16_t sign = (bits >> 16) & 0x8000u;
  int32_t exp = static_cast<int32_t>((bits >> 23) & 0xFF) - 127 + 15;
  uint32_t man = bits & 0x7FFFFFu;
  if (((bits >> 23) & 0xFF) == 0xFF) {  // inf/nan
    return sign | 0x7C00u | (man ? 0x200u : 0);
  }
  if (exp >= 31) return sign | 0x7C00u;  // overflow -> inf
  if (exp <= 0) {                        // subnormal/underflow
    if (exp < -10) return sign;
    man |= 0x800000u;
    uint32_t shift = 14 - exp;
    uint16_t h = man >> shift;
    if ((man >> (shift - 1)) & 1) ++h;  // round-nearest
    return sign | h;
  }
  uint16_t h = sign | (exp << 10) | (man >> 13);
  if (man & 0x1000u) ++h;  // round-nearest
  return h;
}

static float bf16_to_float(uint16_t b) {
  uint32_t bits = static_cast<uint32_t>(b) << 16;
  float f;
  std::memcpy(&f, &bits, 4);
  return f;
}

static uint16_t float_to_bf16(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  uint32_t lsb = (bits >> 16) & 1;
  bits += 0x7FFFu + lsb;  // round-to-nearest-even
  return static_cast<uint16_t>(bits >> 16);
}

// fp8 codecs (ml_dtypes float8_e4m3fn / float8_e5m2 twins): shared with
// the compiled combine kernels via bs_codec.h — ONE implementation, held
// bit-identical to the ml_dtypes parity corpus (full 256-code product,
// ±0/NaN/inf) by tests/test_combine_native.py. e4m3fn: 1-4-3 bias 7, no
// inf; e5m2: 1-5-2 bias 15, IEEE-style inf/NaN.
static float fp8_decode(uint8_t v, bool e4m3) {
  return e4m3 ? bsc_f8_to_float(v, 3, 7, 0) : bsc_f8_to_float(v, 2, 15, 1);
}

static uint8_t fp8_encode(float f, bool e4m3) {
  return e4m3 ? bsc_float_to_f8(f, 3, 7, 0) : bsc_float_to_f8(f, 2, 15, 1);
}

// scale-block wire dtype -> bs_codec quantizer kind (quant._QCODES twin:
// the wire qcode IS the dtype code, 6 = int8 / 8 = e4m3fn / 9 = e5m2)
static int bs_qk_of(uint8_t dt) {
  switch (dt) {
    case DT_I8: return BSC_QK_I8;
    case DT_F8E4M3: return BSC_QK_E4M3;
    case DT_F8E5M2: return BSC_QK_E5M2;
    default: return -1;
  }
}

// read element i of a typed buffer as double
static double load_elem(const uint8_t* p, uint8_t dt, size_t i) {
  switch (dt) {
    case DT_F32: { float v; std::memcpy(&v, p + 4 * i, 4); return v; }
    case DT_F64: { double v; std::memcpy(&v, p + 8 * i, 8); return v; }
    case DT_I32: { int32_t v; std::memcpy(&v, p + 4 * i, 4); return v; }
    case DT_I64: { int64_t v; std::memcpy(&v, p + 8 * i, 8); return (double)v; }
    case DT_F16: { uint16_t v; std::memcpy(&v, p + 2 * i, 2); return half_to_float(v); }
    case DT_BF16: { uint16_t v; std::memcpy(&v, p + 2 * i, 2); return bf16_to_float(v); }
    case DT_F8E4M3: return fp8_decode(p[i], true);
    case DT_F8E5M2: return fp8_decode(p[i], false);
    case DT_I8: return reinterpret_cast<const int8_t*>(p)[i];
    default: return p[i];
  }
}

static void store_elem(uint8_t* p, uint8_t dt, size_t i, double v) {
  switch (dt) {
    case DT_F32: { float f = (float)v; std::memcpy(p + 4 * i, &f, 4); break; }
    case DT_F64: std::memcpy(p + 8 * i, &v, 8); break;
    case DT_I32: { int32_t x = (int32_t)llround(v); std::memcpy(p + 4 * i, &x, 4); break; }
    case DT_I64: { int64_t x = (int64_t)llround(v); std::memcpy(p + 8 * i, &x, 8); break; }
    case DT_F16: { uint16_t h = float_to_half((float)v); std::memcpy(p + 2 * i, &h, 2); break; }
    case DT_BF16: { uint16_t b = float_to_bf16((float)v); std::memcpy(p + 2 * i, &b, 2); break; }
    case DT_F8E4M3: p[i] = fp8_encode((float)v, true); break;
    case DT_F8E5M2: p[i] = fp8_encode((float)v, false); break;
    case DT_I8: reinterpret_cast<int8_t*>(p)[i] = (int8_t)llround(v); break;
    default: p[i] = (uint8_t)llround(v); break;
  }
}

// 64-bit integer exactness: int64 sums beyond 2^53 lose precision through
// double; keep a dedicated integer path when both sides are integral.
static bool is_integral(uint8_t dt) {
  return dt == DT_I32 || dt == DT_I64 || dt == DT_I8 || dt == DT_U8;
}

static int64_t load_int(const uint8_t* p, uint8_t dt, size_t i) {
  switch (dt) {
    case DT_I32: { int32_t v; std::memcpy(&v, p + 4 * i, 4); return v; }
    case DT_I64: { int64_t v; std::memcpy(&v, p + 8 * i, 8); return v; }
    case DT_I8: return reinterpret_cast<const int8_t*>(p)[i];
    default: return p[i];
  }
}

static void store_int(uint8_t* p, uint8_t dt, size_t i, int64_t v) {
  switch (dt) {
    case DT_I32: { int32_t x = (int32_t)v; std::memcpy(p + 4 * i, &x, 4); break; }
    case DT_I64: std::memcpy(p + 8 * i, &v, 8); break;
    case DT_I8: reinterpret_cast<int8_t*>(p)[i] = (int8_t)v; break;
    default: p[i] = (uint8_t)v; break;
  }
}

// convert n elements between dtypes (the compression-lane plugins'
// capability: fp_hp/hp_fp_stream_conv, generalized to all dtype pairs)
static std::vector<uint8_t> convert(const std::vector<uint8_t>& src,
                                    uint8_t sdt, uint8_t ddt, size_t n) {
  if (sdt == ddt) return src;
  std::vector<uint8_t> dst(n * dtype_size(ddt));
  if (is_integral(sdt) && is_integral(ddt)) {
    for (size_t i = 0; i < n; ++i) store_int(dst.data(), ddt, i, load_int(src.data(), sdt, i));
  } else {
    for (size_t i = 0; i < n; ++i) store_elem(dst.data(), ddt, i, load_elem(src.data(), sdt, i));
  }
  return dst;
}

// a = func(a, b), both in dtype dt, n elements (reduce_sum plugin parity,
// extended to max/min/prod like the XRT driver's enum set)
static void reduce_inplace(std::vector<uint8_t>& a,
                           const std::vector<uint8_t>& b, uint8_t dt,
                           uint8_t func, size_t n) {
  if (is_integral(dt)) {
    for (size_t i = 0; i < n; ++i) {
      int64_t x = load_int(a.data(), dt, i), y = load_int(b.data(), dt, i);
      int64_t r = func == FN_SUM ? x + y : func == FN_MAX ? std::max(x, y)
                  : func == FN_MIN ? std::min(x, y) : x * y;
      store_int(a.data(), dt, i, r);
    }
  } else {
    for (size_t i = 0; i < n; ++i) {
      double x = load_elem(a.data(), dt, i), y = load_elem(b.data(), dt, i);
      double r = func == FN_SUM ? x + y : func == FN_MAX ? std::max(x, y)
                 : func == FN_MIN ? std::min(x, y) : x * y;
      store_elem(a.data(), dt, i, r);
    }
  }
}

// ---------------------------------------------------------------------------
// scale-block packed wire segments (accl_tpu/quant.py twins): the
// self-describing [magic 0xB5 | qcode u8 | block u16 | count u32 |
// f32 scales | q payload] layout both tiers emit and parse, quantized
// and dequantized through the shared bs_codec entry points
// ---------------------------------------------------------------------------
static void bs_to_f32(const std::vector<uint8_t>& in, uint8_t dt,
                      std::vector<float>& out) {
  if (dt == DT_F32) {
    std::memcpy(out.data(), in.data(), out.size() * 4);
    return;
  }
  for (size_t i = 0; i < out.size(); ++i)
    out[i] = (float)load_elem(in.data(), dt, i);
}

static std::vector<uint8_t> bs_from_f32(const std::vector<float>& f,
                                        uint8_t dt) {
  std::vector<uint8_t> out(f.size() * dtype_size(dt));
  if (dt == DT_F32) {
    std::memcpy(out.data(), f.data(), out.size());
    return out;
  }
  for (size_t i = 0; i < f.size(); ++i) store_elem(out.data(), dt, i, f[i]);
  return out;
}

// quantize `count` elements of `data` (stored as udtype) into one packed
// segment (quant.quantize_packed parity: wire qcode IS the dtype code)
static std::vector<uint8_t> bs_pack(const std::vector<uint8_t>& data,
                                    uint8_t udtype, uint8_t cdtype,
                                    uint32_t block, uint64_t count) {
  std::vector<float> f(count);
  bs_to_f32(data, udtype, f);
  int qk = bs_qk_of(cdtype);
  uint64_t nb = (count + block - 1) / block;
  std::vector<uint8_t> out(8 + 4 * nb + count);
  out[0] = 0xB5;
  out[1] = cdtype;
  out[2] = (uint8_t)block;
  out[3] = (uint8_t)(block >> 8);
  out[4] = (uint8_t)count;
  out[5] = (uint8_t)(count >> 8);
  out[6] = (uint8_t)(count >> 16);
  out[7] = (uint8_t)(count >> 24);
  bsc_quantize(qk, (ptrdiff_t)block,
               f.data(), reinterpret_cast<float*>(out.data() + 8),
               out.data() + 8 + 4 * nb, (ptrdiff_t)count);
  return out;
}

// parsed packed segment, held raw so the fused path can bsc_combine
// straight off the wire bytes (quant.dequant_combine_packed parity)
struct BsSeg {
  bool valid = false;
  int qk = -1;
  uint32_t block = 0;
  uint64_t count = 0;
  std::vector<uint8_t> seg;
  const float* scales() const {
    return reinterpret_cast<const float*>(seg.data() + 8);
  }
  const uint8_t* q() const {
    return seg.data() + 8 + 4 * ((count + block - 1) / block);
  }
};

static bool bs_parse(std::vector<uint8_t>&& payload, BsSeg* out) {
  if (payload.size() < 8 || payload[0] != 0xB5) return false;
  int qk = bs_qk_of(payload[1]);
  uint32_t block = (uint32_t)payload[2] | ((uint32_t)payload[3] << 8);
  uint64_t count = (uint64_t)payload[4] | ((uint64_t)payload[5] << 8) |
                   ((uint64_t)payload[6] << 16) | ((uint64_t)payload[7] << 24);
  if (qk < 0 || block < 32 || block > 4096 || (block & (block - 1)))
    return false;
  uint64_t nb = (count + block - 1) / block;
  if (payload.size() != 8 + 4 * nb + count) return false;
  out->qk = qk;
  out->block = block;
  out->count = count;
  out->seg = std::move(payload);
  out->valid = true;
  return true;
}

// ---------------------------------------------------------------------------
// envelope + rx pool (rxbuf_offload / seek_rx_buffer / wait_on_rx parity)
// ---------------------------------------------------------------------------
struct Envelope {
  uint32_t src, dst, tag, seqn, comm_id;
  uint8_t strm, dtype;
  uint64_t nbytes;
  // trailing integrity word (crc32c over the payload bytes): present only
  // when the sender appended one — frames from unchecksummed senders
  // parse with has_csum false and skip verification (protocol.py twins)
  bool has_csum = false;
  uint32_t csum = 0;
};

struct RxBuffer {
  bool reserved = false;
  Envelope env{};
  std::vector<uint8_t> payload;
};

class RxBufferPool {
 public:
  RxBufferPool(size_t nbufs, size_t bufsize)
      : bufs_(nbufs), bufsize_(bufsize) {}

  uint32_t ingest(const Envelope& env, std::vector<uint8_t>&& payload,
                  double timeout_s) {
    std::unique_lock<std::mutex> lk(mu_);
    if (payload.size() > bufsize_) { error_word |= E_DMA_SIZE; return E_DMA_SIZE; }
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::duration<double>(timeout_s);
    for (;;) {
      for (auto& b : bufs_) {
        if (!b.reserved) {
          b.reserved = true;
          b.env = env;
          b.payload = std::move(payload);
          cv_.notify_all();
          return E_OK;
        }
      }
      if (cv_.wait_until(lk, deadline) == std::cv_status::timeout) {
        error_word |= E_SPARE_OVERFLOW;
        return E_SPARE_OVERFLOW;
      }
    }
  }

  bool seek(uint32_t src, uint32_t tag, uint32_t seqn, uint32_t comm_id,
            double timeout_s, Envelope* env_out,
            std::vector<uint8_t>* payload_out) {
    std::unique_lock<std::mutex> lk(mu_);
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::duration<double>(timeout_s);
    for (;;) {
      for (auto& b : bufs_) {
        if (!b.reserved) continue;
        if (b.env.src != src || b.env.seqn != seqn) continue;
        if (b.env.comm_id != comm_id) continue;
        if (tag != TAG_ANY && b.env.tag != tag && b.env.tag != TAG_ANY) continue;
        *env_out = b.env;
        *payload_out = std::move(b.payload);
        b.reserved = false;
        b.payload.clear();
        cv_.notify_all();
        return true;
      }
      if (cv_.wait_until(lk, deadline) == std::cv_status::timeout) return false;
    }
  }

  std::string describe() {
    std::unique_lock<std::mutex> lk(mu_);
    size_t occ = 0;
    for (auto& b : bufs_) occ += b.reserved ? 1 : 0;
    char line[128];
    snprintf(line, sizeof line, "RX pool: %zu x %zuB, %zu reserved (native)",
             bufs_.size(), bufsize_, occ);
    return std::string(line);
  }

  void reset() {
    std::unique_lock<std::mutex> lk(mu_);
    for (auto& b : bufs_) { b.reserved = false; b.payload.clear(); }
    error_word = 0;
    cv_.notify_all();
  }

  std::atomic<uint32_t> error_word{0};

 private:
  std::vector<RxBuffer> bufs_;
  size_t bufsize_;
  std::mutex mu_;
  std::condition_variable cv_;
};

// ---------------------------------------------------------------------------
// device memory (SimBuffer fake-phys-addr model)
// ---------------------------------------------------------------------------
class DeviceMemory {
 public:
  void alloc(uint64_t addr, uint64_t nbytes) {
    std::lock_guard<std::mutex> lk(mu_);
    regions_[addr] = std::vector<uint8_t>(nbytes, 0);
  }
  void free_region(uint64_t addr) {
    std::lock_guard<std::mutex> lk(mu_);
    regions_.erase(addr);
  }
  bool write(uint64_t addr, const uint8_t* data, uint64_t nbytes) {
    std::lock_guard<std::mutex> lk(mu_);
    auto* r = resolve(addr, nbytes);
    if (!r) return false;
    std::memcpy(r->second.data() + (addr - r->first), data, nbytes);
    return true;
  }
  bool read(uint64_t addr, uint8_t* out, uint64_t nbytes) {
    std::lock_guard<std::mutex> lk(mu_);
    auto* r = resolve(addr, nbytes);
    if (!r) return false;
    std::memcpy(out, r->second.data() + (addr - r->first), nbytes);
    return true;
  }
  bool valid(uint64_t addr, uint64_t nbytes) {
    // address-range check WITHOUT touching data: callers validate before
    // sizing scratch buffers so a bogus descriptor cannot force a huge
    // zero-filled allocation
    std::lock_guard<std::mutex> lk(mu_);
    return resolve(addr, nbytes) != nullptr;
  }

 private:
  std::pair<const uint64_t, std::vector<uint8_t>>* resolve(uint64_t addr,
                                                           uint64_t nbytes) {
    auto it = regions_.upper_bound(addr);
    if (it == regions_.begin()) return nullptr;
    --it;
    if (addr >= it->first && addr + nbytes <= it->first + it->second.size())
      return &*it;
    return nullptr;
  }
  std::map<uint64_t, std::vector<uint8_t>> regions_;
  std::mutex mu_;
};

// ---------------------------------------------------------------------------
// communicator (exchange-memory communicator record parity)
// ---------------------------------------------------------------------------
struct RankInfo {
  uint32_t global_rank;
  std::string host;
  uint16_t cmd_port;
  uint32_t inbound_seq = 0, outbound_seq = 0;
};

struct Communicator {
  uint32_t comm_id = 0;
  uint32_t local_rank = 0;
  std::vector<RankInfo> ranks;
  // multi-tenant service grouping (optional trailing MSG_CONFIG_COMM
  // record; empty for older clients and ungrouped comms). The native
  // tier carries the label for attribution parity with the Python
  // daemon — per-tenant quotas live on the service layer upstream.
  std::string tenant;
  uint32_t size() const { return static_cast<uint32_t>(ranks.size()); }
  uint32_t my_global() const { return ranks[local_rank].global_rank; }
};

// ---------------------------------------------------------------------------
// eth fabric: lazy peer dial + accept/ingest loops (zmq pub/sub wire parity)
// ---------------------------------------------------------------------------
class RankDaemon;  // fwd

class EthFabric {
 public:
  // stack: "tcp" (framed stream) or "udp" (datagram packetizer/reassembly;
  // wire-compatible with the Python UdpEthFabric — same 12B fragment
  // header {sender u32, msg_id u32, frag u16, nfrags u16} + same 30B eth
  // header, so mixed C++/Python worlds interoperate on either stack)
  static constexpr size_t kMaxPkt = 1408;        // reference MTU 1536B
  static constexpr double kPartialTtl = 30.0;    // GC for lost fragments
  static constexpr size_t kQueueDepth = 64;      // per-sender delivery
  // bound, must match Python UdpEthFabric.QUEUE_DEPTH (mixed worlds)

  EthFabric(uint32_t me, uint16_t listen_port, RankDaemon* daemon,
            bool udp = false);
  ~EthFabric();
  void learn_peer(uint32_t grank, const std::string& host, uint16_t eth_port) {
    std::lock_guard<std::mutex> lk(mu_);
    peer_addrs_[grank] = {host, eth_port};
  }
  bool send_msg(const Envelope& env, const std::vector<uint8_t>& payload);
  void stop();
  bool is_udp() const { return udp_; }
  bool ok() const { return listen_fd_ >= 0; }  // bind succeeded
  bool listening() const { return ok() && !stopping_.load(); }
  uint32_t connect_all();   // openCon parity (eager session open)
  void disconnect_all();    // close per-peer sessions (lazy re-dial later)
  bool csum_enabled() const { return csum_; }
  int retx_window() const { return retx_window_; }
  void reset_retx();  // soft reset: retx ring + trackers restart at zero

 private:
  void accept_loop();
  void recv_loop(int fd);
  void udp_recv_loop();
  void udp_handle(const uint8_t* dgram, size_t len);
  void deliver(uint32_t sender, Envelope&& env,
               std::vector<uint8_t>&& payload);
  static std::vector<uint8_t> encode_eth(const Envelope& env,
                                         const std::vector<uint8_t>& payload,
                                         bool with_msg_byte);
  static bool decode_eth(const uint8_t* p, size_t len, Envelope& env,
                         std::vector<uint8_t>& payload);
  uint32_t me_;
  int listen_fd_ = -1;
  RankDaemon* daemon_;
  bool udp_;
  std::vector<int> inbound_fds_;  // accepted eth connections (guarded mu_)
  std::map<uint32_t, int> peers_;
  // per-peer send mutexes: one slow peer's TCP backpressure must not stall
  // sends to other peers (mu_ guards only lookup/dial)
  std::map<uint32_t, std::unique_ptr<std::mutex>> peer_mus_;
  std::map<uint32_t, std::pair<std::string, uint16_t>> peer_addrs_;
  std::mutex mu_;
  std::vector<std::thread> threads_;
  std::atomic<bool> stopping_{false};
  // udp state: message ids, reassembly, per-sender delivery workers (a
  // blocked ingest for one peer must not head-of-line-block the others
  // behind the single datagram recv thread)
  uint32_t next_msg_id_ = 0;
  struct Partial {
    double deadline;
    uint16_t nfrags;
    std::map<uint16_t, std::vector<uint8_t>> frags;
  };
  std::map<std::pair<uint32_t, uint32_t>, Partial> partial_;
  struct DeliverQ {
    std::deque<std::pair<Envelope, std::vector<uint8_t>>> q;
    std::mutex mu;
    std::condition_variable cv;
    bool stop = false;
  };
  std::map<uint32_t, std::unique_ptr<DeliverQ>> dqs_;

  // ---- reliability endpoint (emulator/reliability.RetxEndpoint twin) ----
  // Sender side: every strm=0 data frame is kept in a per-(dst, comm)
  // in-flight ring (the fully-encoded eth frame, so a retransmission is
  // one sendto) until acked; an RTO scan thread re-fires expired flights
  // with exponential backoff, and selective-ack gaps trigger a one-shot
  // NACK fast-retransmit. Receiver side: a per-(src, comm) cum+ooo
  // tracker dedups/horizon-bounds arrivals and acks every enqueued frame
  // on the strm=2 control lane. Engaged only on the UDP stack with a
  // nonzero window ($ACCL_TPU_RETX_WINDOW), like the python fabrics.
  static constexpr double kRtoS = 0.05;       // pre-sample default
  static constexpr double kRtoMinS = 0.005;
  static constexpr double kRtoMaxS = 1.0;
  static constexpr int kMaxTries = 10;
  static constexpr uint32_t kSeqnHorizon = 1u << 18;
  struct Flight {
    std::vector<uint8_t> frame;  // encoded eth frame (header+payload+csum)
    double deadline = 0.0;
    double t0 = 0.0;
    int tries = 0;
    bool fast = false;  // one-shot NACK fast-retransmit already fired
  };
  bool udp_send_frame(uint32_t dst, const std::vector<uint8_t>& frame);
  void track(const Envelope& env, const std::vector<uint8_t>& frame);
  void on_ack(uint32_t src, uint32_t comm_id, uint32_t cum,
              const std::vector<uint32_t>& sel);
  void send_ack(uint32_t dst, uint32_t comm_id, uint32_t cum,
                const std::vector<uint32_t>& sel);
  void retx_tick_loop();
  double cur_rto_locked() const;
  double rto_of_locked(int tries, uint32_t dst, uint32_t comm_id,
                       uint32_t seqn) const;
  void note_rtt_locked(const Flight& fl);
  bool csum_ = false;
  int retx_window_ = 0;
  // deterministic TX chaos for the mixed-world sweep
  // ($ACCL_TPU_CHAOS_TX_DROP / $ACCL_TPU_CHAOS_TX_CORRUPT = N: every
  // Nth outgoing DATA frame is dropped / payload-bit-flipped before the
  // socket). ACK frames are exempt (recovery must never turn against
  // itself); the in-flight ring keeps the intact original, so an RTO
  // resend — a fresh counter draw — eventually gets through.
  int chaos_drop_every_ = 0, chaos_corrupt_every_ = 0;
  std::atomic<uint64_t> chaos_tx_n_{0};
  std::mutex retx_mu_;
  std::condition_variable retx_space_;
  std::map<std::pair<uint32_t, uint32_t>, std::map<uint32_t, Flight>> ring_;
  size_t inflight_ = 0;
  // receiver tracker: (src, comm) -> (cum expected seqn, out-of-order set)
  std::map<std::pair<uint32_t, uint32_t>,
           std::pair<uint32_t, std::set<uint32_t>>> rcv_;
  double srtt_ = -1.0, rttvar_ = 0.0;  // Jacobson/Karels, Karn-filtered
};

// ---------------------------------------------------------------------------
// move micro-ops (move_instruction parity) + control plane expansions
// (ccl_offload_control.c:502-1098 ring algorithms, re-derived)
// ---------------------------------------------------------------------------
enum MoveMode : uint8_t { M_NONE = 0, M_IMM = 1, M_ON_RECV = 2, M_STREAM = 3 };

struct Operand {
  MoveMode mode = M_NONE;
  uint64_t addr = 0;
  uint32_t src_rank = 0;  // comm-local, for ON_RECV
  uint32_t tag = TAG_ANY;
  bool compressed = false;
};

struct Move {
  uint64_t count = 0;
  Operand op0, op1, res;
  int func = -1;  // -1 = passthrough
  bool res_remote = false, res_local = false;
  uint32_t dst_rank = 0;  // comm-local
  uint32_t tag = TAG_ANY;
  bool eth_compressed = false;
  bool remote_stream = false;
};

struct CallCtx {
  uint32_t world, me;
  uint8_t udtype, cdtype;
  uint64_t max_seg;
  uint8_t compression;
  uint8_t stream = 0;  // StreamFlags: 1 = OP0_STREAM, 2 = RES_STREAM
  // scale-block size for C_BLOCK_SCALED calls (elements per f32 scale,
  // pow2 in [32, 4096]); 0 when the call is not block-scaled
  uint32_t qblock = 0;

  bool block_scaled() const {
    return qblock != 0 && (compression & C_BLOCK_SCALED) != 0;
  }
  size_t ebytes(bool compressed) const {
    return dtype_size(compressed ? cdtype : udtype);
  }
  uint64_t seg_elems() const {
    bool ethc = (compression & C_ETH) != 0;
    size_t e = dtype_size(ethc ? cdtype : udtype);
    if (ethc && block_scaled()) {
      // packed-segment budget (quant.seg_elems twin): 8B header + one
      // f32 scale per block (worst case 1 bit/elem at the 32-elem
      // minimum) + partial-block slack must fit max_seg
      if (max_seg <= 12) return 1;
      uint64_t s = 8 * (max_seg - 12) / (8 * (uint64_t)e + 1);
      return s ? s : 1;
    }
    uint64_t s = max_seg / (e ? e : 1);
    return s ? s : 1;
  }
};

// remap the RES compressed-ness onto OP0: used whenever a move reads a
// RES-typed (dst-resident) slot as its operand — relays from dst, folds
// into dst, the bcast after a non-fused reduce (moveengine.res_as_op0)
static CallCtx res_as_op0(const CallCtx& c) {
  CallCtx rc = c;
  rc.compression = (c.compression & ~uint8_t(C_OP0)) |
                   ((c.compression & C_RES) ? C_OP0 : 0);
  return rc;
}

static void push_send(std::vector<Move>& mv, const CallCtx& c, uint64_t count,
                      uint64_t src, uint32_t dst, uint32_t tag,
                      bool remote_stream = false) {
  uint64_t seg = c.seg_elems();
  size_t eb = c.ebytes(c.compression & C_OP0);
  bool op0_stream = (c.stream & 1) != 0;
  for (uint64_t off = 0; off < count; off += seg) {
    Move m;
    m.count = std::min(seg, count - off);
    if (op0_stream)
      m.op0 = {M_STREAM, 0, 0, TAG_ANY, false};
    else
      m.op0 = {M_IMM, src + off * eb, 0, TAG_ANY,
               (c.compression & C_OP0) != 0};
    m.res_remote = true;
    m.dst_rank = dst;
    m.tag = tag;
    m.eth_compressed = (c.compression & C_ETH) != 0;
    m.remote_stream = remote_stream;
    mv.push_back(m);
  }
}

static void push_recv(std::vector<Move>& mv, const CallCtx& c, uint64_t count,
                      uint32_t src, uint64_t dst, uint32_t tag) {
  uint64_t seg = c.seg_elems();
  size_t eb = c.ebytes(c.compression & C_RES);
  bool res_stream = (c.stream & 2) != 0;  // RES_STREAM: local stream sink
  for (uint64_t off = 0; off < count; off += seg) {
    Move m;
    m.count = std::min(seg, count - off);
    m.op1 = {M_ON_RECV, 0, src, tag, false};
    if (res_stream)
      m.res = {M_STREAM, 0, 0, TAG_ANY, false};
    else
      m.res = {M_IMM, dst + off * eb, 0, TAG_ANY,
               (c.compression & C_RES) != 0};
    m.res_local = true;
    m.eth_compressed = (c.compression & C_ETH) != 0;
    mv.push_back(m);
  }
}

static void push_copy(std::vector<Move>& mv, const CallCtx& c, uint64_t count,
                      uint64_t src, uint64_t dst) {
  Move m;
  m.count = count;
  if (c.stream & 1)
    m.op0 = {M_STREAM, 0, 0, TAG_ANY, false};
  else
    m.op0 = {M_IMM, src, 0, TAG_ANY, (c.compression & C_OP0) != 0};
  if (c.stream & 2)
    m.res = {M_STREAM, 0, 0, TAG_ANY, false};
  else
    m.res = {M_IMM, dst, 0, TAG_ANY, (c.compression & C_RES) != 0};
  m.res_local = true;
  mv.push_back(m);
}

static void push_frr(std::vector<Move>& mv, const CallCtx& c, uint64_t count,
                     int func, uint32_t src, uint64_t op0, uint64_t dst,
                     uint32_t tag) {
  // fused recv-reduce into local dst
  uint64_t seg = c.seg_elems();
  size_t e0 = c.ebytes(c.compression & C_OP0);
  size_t er = c.ebytes(c.compression & C_RES);
  for (uint64_t off = 0; off < count; off += seg) {
    Move m;
    m.count = std::min(seg, count - off);
    m.op0 = {M_IMM, op0 + off * e0, 0, TAG_ANY, (c.compression & C_OP0) != 0};
    m.op1 = {M_ON_RECV, 0, src, tag, false};
    m.res = {M_IMM, dst + off * er, 0, TAG_ANY, (c.compression & C_RES) != 0};
    m.func = func;
    m.res_local = true;
    m.eth_compressed = (c.compression & C_ETH) != 0;
    mv.push_back(m);
  }
}

static void push_frrs(std::vector<Move>& mv, const CallCtx& c, uint64_t count,
                      int func, uint32_t src, uint32_t dst_rank, uint64_t op0,
                      uint32_t tag) {
  // fused recv-reduce-send to the next ring neighbor
  uint64_t seg = c.seg_elems();
  size_t e0 = c.ebytes(c.compression & C_OP0);
  for (uint64_t off = 0; off < count; off += seg) {
    Move m;
    m.count = std::min(seg, count - off);
    m.op0 = {M_IMM, op0 + off * e0, 0, TAG_ANY, (c.compression & C_OP0) != 0};
    m.op1 = {M_ON_RECV, 0, src, tag, false};
    m.func = func;
    m.res_remote = true;
    m.dst_rank = dst_rank;
    m.tag = tag;
    m.eth_compressed = (c.compression & C_ETH) != 0;
    mv.push_back(m);
  }
}

// internal scratch far above the drivers' 4K bump allocators; used by the
// barrier rendezvous (1-element allreduce) — matches the Python daemon
static const uint64_t BARRIER_SCRATCH_ADDR = 1ull << 60;

// expand one call into a move program; mirrors the ring algorithms
// (decreasing-rank data flow: rank r forwards to r-1, receives from r+1)
// and the per-call algorithm variants of moveengine.expand_call
static uint32_t expand(std::vector<Move>& mv, const CallCtx& c_in, uint8_t op,
                       int func, uint64_t count, uint32_t root, uint32_t tag,
                       uint64_t a0, uint64_t a1, uint64_t a2,
                       uint8_t alg = ALG_AUTO,
                       std::string* feature = nullptr) {
  // stream flags apply only to copy/combine/send/recv
  // (moveengine.expand_call parity) — a collective's internal copies
  // must never source/sink the external-kernel stream ports
  CallCtx c = c_in;
  if (op != OP_COPY && op != OP_COMBINE && op != OP_SEND && op != OP_RECV)
    c.stream = 0;
  if (c.compression & C_BLOCK_SCALED) {
    // scale-block wire executes natively (bs_codec twins of quant.py) —
    // but only onto quantizable wire dtypes; anything else is a typed,
    // NAMED config error so the driver surfaces the gap precisely
    if (bs_qk_of(c.cdtype) < 0) {
      if (feature) *feature = "block-scaled wire dtype";
      return E_COMPRESSION;
    }
  }
  const uint32_t W = c.world, me = c.me;
  size_t eb = c.ebytes(c.compression & C_OP0);
  size_t ebr = c.ebytes(c.compression & C_RES);
  // validate the (op, algorithm) pair; AUTO resolves to the default below
  if (alg != ALG_AUTO) {
    bool ok;
    switch (op) {
      case OP_BCAST: ok = alg == ALG_ROUND_ROBIN || alg == ALG_TREE; break;
      case OP_SCATTER: ok = alg == ALG_ROUND_ROBIN; break;
      case OP_GATHER: case OP_REDUCE: case OP_ALLGATHER:
        ok = alg == ALG_RING || alg == ALG_ROUND_ROBIN; break;
      case OP_ALLREDUCE:
        ok = alg == ALG_RING || alg == ALG_FUSED_RING ||
             alg == ALG_NON_FUSED; break;
      case OP_REDUCE_SCATTER: ok = alg == ALG_RING; break;
      default: ok = false;
    }
    if (!ok) return E_INVALID;
  }
  switch (op) {
    case OP_NOP: case OP_CONFIG:
      return E_OK;
    case OP_BARRIER: {
      // rendezvous as a 1-element fp32 allreduce on internal scratch;
      // dtype/compression/stream are normalized so barrier semantics do
      // not depend on the descriptor (matches the Python daemon)
      CallCtx bc = c;
      bc.udtype = bc.cdtype = DT_F32;
      bc.compression = C_NONE;
      bc.stream = 0;
      return expand(mv, bc, OP_ALLREDUCE, FN_SUM, 1, 0, TAG_ANY,
                    BARRIER_SCRATCH_ADDR, 0, BARRIER_SCRATCH_ADDR + 4);
    }
    case OP_COPY:
      push_copy(mv, c, count, a0, a2);
      return E_OK;
    case OP_COMBINE: {
      // OP0/RES stream flags route through the external-kernel ports,
      // like copy (combine-from-stream; moveengine.expand_combine twin)
      Move m;
      m.count = count;
      if (c.stream & 1)
        m.op0 = {M_STREAM, 0, 0, TAG_ANY, false};
      else
        m.op0 = {M_IMM, a0, 0, TAG_ANY, (c.compression & C_OP0) != 0};
      m.op1 = {M_IMM, a1, 0, TAG_ANY, (c.compression & C_OP1) != 0};
      if (c.stream & 2)
        m.res = {M_STREAM, 0, 0, TAG_ANY, false};
      else
        m.res = {M_IMM, a2, 0, TAG_ANY, (c.compression & C_RES) != 0};
      m.func = func;
      m.res_local = true;
      mv.push_back(m);
      return E_OK;
    }
    case OP_SEND:
      // RES_STREAM on a send targets the peer's stream port (remote-stream
      // send, matching moveengine.expand_call)
      push_send(mv, c, count, a0, root, tag, (c.stream & 2) != 0);
      return E_OK;
    case OP_RECV:
      push_recv(mv, c, count, root, a2, tag);
      return E_OK;
    case OP_BCAST:
      if (alg == ALG_TREE) {
        // binomial tree: recv once from the parent, forward to sub-roots
        if (W == 1) return E_OK;
        uint32_t vrank = (me + W - root) % W;
        uint32_t mask = 1;
        while (mask < W) {
          if (vrank & mask) {
            uint32_t parent = ((vrank ^ mask) + root) % W;
            push_recv(mv, c, count, parent, a0, TAG_ANY);
            break;
          }
          mask <<= 1;
        }
        for (mask >>= 1; mask; mask >>= 1)
          if (vrank + mask < W)
            push_send(mv, c, count, a0, ((vrank + mask) + root) % W, TAG_ANY);
        return E_OK;
      }
      if (me == root) {
        for (uint32_t r = 0; r < W; ++r)
          if (r != root) push_send(mv, c, count, a0, r, TAG_ANY);
      } else {
        push_recv(mv, c, count, root, a0, TAG_ANY);
      }
      return E_OK;
    case OP_SCATTER:
      if (me == root) {
        for (uint32_t r = 0; r < W; ++r) {
          uint64_t chunk = a0 + (uint64_t)r * count * eb;
          if (r == root) push_copy(mv, c, count, chunk, a2);
          else push_send(mv, c, count, chunk, r, TAG_ANY);
        }
      } else {
        push_recv(mv, c, count, root, a2, TAG_ANY);
      }
      return E_OK;
    case OP_GATHER: {
      if (alg == ALG_ROUND_ROBIN) {
        // direct: non-roots send straight to root
        if (me == root) {
          push_copy(mv, c, count, a0, a2 + (uint64_t)me * count * ebr);
          for (uint32_t r = 0; r < W; ++r)
            if (r != root)
              push_recv(mv, c, count, r, a2 + (uint64_t)r * count * ebr,
                        TAG_ANY);
        } else {
          push_send(mv, c, count, a0, root, TAG_ANY);
        }
        return E_OK;
      }
      uint32_t dist = (me + W - root) % W;
      uint32_t prv = (me + 1) % W, nxt = (me + W - 1) % W;
      if (me == root) {
        push_copy(mv, c, count, a0, a2 + (uint64_t)me * count * ebr);
        for (uint32_t i = 0; i + 1 < W; ++i) {
          uint32_t owner = (root + 1 + i) % W;
          push_recv(mv, c, count, prv, a2 + (uint64_t)owner * count * ebr,
                    TAG_ANY);
        }
      } else {
        push_send(mv, c, count, a0, nxt, TAG_ANY);
        for (uint32_t i = 0; i < W - 1 - dist; ++i) {
          push_recv(mv, c, count, prv, a2, TAG_ANY);
          // relay reads the RES-typed scratch the recv just wrote
          push_send(mv, res_as_op0(c), count, a2, nxt, TAG_ANY);
        }
      }
      return E_OK;
    }
    case OP_ALLGATHER: {
      if (alg == ALG_ROUND_ROBIN) {
        // direct fan-out: send own chunk to every peer, recv W-1 chunks
        push_copy(mv, c, count, a0, a2 + (uint64_t)me * count * ebr);
        for (uint32_t step = 1; step < W; ++step)
          push_send(mv, c, count, a0, (me + step) % W, TAG_ANY);
        for (uint32_t step = 1; step < W; ++step) {
          uint32_t frm = (me + W - step) % W;
          push_recv(mv, c, count, frm, a2 + (uint64_t)frm * count * ebr,
                    TAG_ANY);
        }
        return E_OK;
      }
      uint32_t nxt = (me + 1) % W, prv = (me + W - 1) % W;
      push_copy(mv, c, count, a0, a2 + (uint64_t)me * count * ebr);
      push_send(mv, c, count, a0, nxt, TAG_ANY);
      for (uint32_t i = 0; i + 1 < W; ++i) {
        uint32_t owner = (me + W - 1 - i) % W;
        uint64_t slot = a2 + (uint64_t)owner * count * ebr;
        push_recv(mv, c, count, prv, slot, TAG_ANY);
        // the relay reads the RES-typed slot the recv just wrote
        if (i + 2 < W) push_send(mv, res_as_op0(c), count, slot, nxt, TAG_ANY);
      }
      return E_OK;
    }
    case OP_REDUCE: {
      if (W == 1) { push_copy(mv, c, count, a0, a2); return E_OK; }
      if (alg == ALG_ROUND_ROBIN) {
        // direct: root folds each sender's data into dst sequentially
        if (me != root) {
          push_send(mv, c, count, a0, root, TAG_ANY);
          return E_OK;
        }
        bool first = true;
        for (uint32_t r = 0; r < W; ++r) {
          if (r == root) continue;
          // later folds read dst as op0, whose compressed-ness is the RES flag
          CallCtx rc = first ? c : res_as_op0(c);
          push_frr(mv, rc, count, func, r, first ? a0 : a2, a2, TAG_ANY);
          first = false;
        }
        return E_OK;
      }
      uint32_t nxt = (me + W - 1) % W, prv = (me + 1) % W;
      if ((me + W - root) % W == W - 1) {
        push_send(mv, c, count, a0, nxt, TAG_ANY);
      } else if (me == root) {
        push_frr(mv, c, count, func, prv, a0, a2, TAG_ANY);
      } else {
        push_frrs(mv, c, count, func, prv, nxt, a0, TAG_ANY);
      }
      return E_OK;
    }
    case OP_REDUCE_SCATTER: {
      if (W == 1) { push_copy(mv, c, count, a0, a2); return E_OK; }
      uint32_t nxt = (me + W - 1) % W, prv = (me + 1) % W;
      push_send(mv, c, count, a0 + (uint64_t)((me + 1) % W) * count * eb, nxt,
                TAG_ANY);
      for (uint32_t i = 1; i < W; ++i) {
        uint32_t chunk = (me + 1 + i) % W;
        uint64_t op0 = a0 + (uint64_t)chunk * count * eb;
        if (i + 1 < W) push_frrs(mv, c, count, func, prv, nxt, op0, TAG_ANY);
        else push_frr(mv, c, count, func, prv, op0, a2, TAG_ANY);
      }
      return E_OK;
    }
    case OP_ALLREDUCE: {
      if (W == 1) { push_copy(mv, c, count, a0, a2); return E_OK; }
      if (alg == ALG_NON_FUSED) {
        // ring reduce to rank 0, then broadcast of dst
        uint32_t err = expand(mv, c, OP_REDUCE, func, count, 0, tag, a0, 0,
                              a2, ALG_RING);
        if (err) return err;
        return expand(mv, res_as_op0(c), OP_BCAST, func, count, 0, tag, a2,
                      0, 0, ALG_AUTO);
      }
      uint64_t bulk = count / W;
      uint64_t tail = count - bulk * (W - 1);
      auto clen = [&](uint32_t ch) { return ch == W - 1 ? tail : bulk; };
      auto coff = [&](uint32_t ch) { return (uint64_t)ch * bulk; };
      uint32_t nxt = (me + W - 1) % W, prv = (me + 1) % W;
      // phase 1: ring reduce-scatter
      uint32_t c0 = (me + 1) % W;
      if (clen(c0)) push_send(mv, c, clen(c0), a0 + coff(c0) * eb, nxt, TAG_ANY);
      for (uint32_t i = 1; i < W; ++i) {
        uint32_t ch = (me + 1 + i) % W;
        if (!clen(ch)) continue;
        if (i + 1 < W)
          push_frrs(mv, c, clen(ch), func, prv, nxt, a0 + coff(ch) * eb, TAG_ANY);
        else
          push_frr(mv, c, clen(ch), func, prv, a0 + coff(ch) * eb,
                   a2 + coff(ch) * ebr, TAG_ANY);
      }
      // phase 2: ring allgather from dst — every read sources the RES-typed
      // dst buffer, so the OP0 flag is substituted with the RES flag
      CallCtx p2 = res_as_op0(c);
      if (clen(me)) push_send(mv, p2, clen(me), a2 + coff(me) * ebr, nxt, TAG_ANY);
      for (uint32_t i = 1; i < W; ++i) {
        uint32_t ch = (me + i) % W;
        if (!clen(ch)) continue;
        uint64_t slot = a2 + coff(ch) * ebr;
        push_recv(mv, c, clen(ch), prv, slot, TAG_ANY);
        if (i + 1 < W) push_send(mv, p2, clen(ch), slot, nxt, TAG_ANY);
      }
      return E_OK;
    }
    case OP_ALLTOALL: {
      push_copy(mv, c, count, a0 + (uint64_t)me * count * eb,
                a2 + (uint64_t)me * count * ebr);
      for (uint32_t step = 1; step < W; ++step) {
        uint32_t to = (me + step) % W, frm = (me + W - step) % W;
        push_send(mv, c, count, a0 + (uint64_t)to * count * eb, to, TAG_ANY);
        push_recv(mv, c, count, frm, a2 + (uint64_t)frm * count * ebr, TAG_ANY);
      }
      return E_OK;
    }
    case OP_ALLTOALLV:
      // count vectors arrive in a trailing record this daemon does not
      // parse; reject typed AND named (the feature name rides in the
      // status-reply payload) so the gap surfaces as a capability error,
      // never as a hung or mismatched fixed-count exchange against
      // Python-tier peers
      if (feature) *feature = "alltoallv";
      return E_NOT_IMPLEMENTED;
    default:
      return E_INVALID;
  }
}

// Wait budgets and timeouts arrive on the wire as attacker-controlled
// values: NaN, Inf, negative, or absurdly large values must never reach
// wait_until's time_point conversion (UB for non-finite, a wedged
// serving thread for huge finite ones).
static double sane_budget(double b, bool configured = false) {
  if (!(b >= 0.0)) {  // NaN and negatives
    // 0s means every wait times out immediately — never coerce a
    // deliberate setting there silently
    if (configured)
      std::fprintf(stderr,
                   "[cclo_emud] configured timeout %f is not a "
                   "non-negative number; coerced to 0s\n", b);
    return 0.0;
  }
  if (b > 3600.0) {
    // a deliberate client setting above the 1 h ceiling is a user
    // mistake worth surfacing, not a silent truncation
    if (configured && std::isfinite(b))
      std::fprintf(stderr,
                   "[cclo_emud] configured timeout %.0fs exceeds the "
                   "3600s ceiling; clamped\n", b);
    return 3600.0;
  }
  return b;
}

// ---------------------------------------------------------------------------
// the daemon
// ---------------------------------------------------------------------------
class RankDaemon {
 public:
  RankDaemon(uint32_t rank, uint32_t world, uint16_t port_base, size_t nbufs,
             size_t bufsize, bool udp = false)
      : rank_(rank), world_(world), port_base_(port_base),
        pool_(nbufs, bufsize), bufsize_(bufsize), nbufs_(nbufs),
        max_seg_(bufsize),
        eth_(std::make_unique<EthFabric>(
            rank, static_cast<uint16_t>(port_base + world + rank), this,
            udp)) {
    if (!eth_->ok()) {  // startup bind failure is fatal, like before
      fprintf(stderr, "rank %u: eth port %u bind failed\n", rank,
              port_base + world + rank);
      exit(1);
    }
    mem_.alloc(BARRIER_SCRATCH_ADDR, 8);  // barrier rendezvous scratch
    worker_ = std::thread([this] { call_worker(); });
  }

  void ingest(const Envelope& env, std::vector<uint8_t>&& payload) {
    if (env.strm >= 2) return;  // control lanes (emulator/protocol.py):
    // retransmission ACKs (strm=2) are consumed by the UDP fabric's
    // deliver() before this point; heartbeat/RMA lanes (strm>=3) stay
    // python-tier features — ignore them rather than stream-deliver
    // garbage into the kernel ports
    if (env.strm) {
      std::lock_guard<std::mutex> lk(stream_mu_);
      stream_in_.push_back({env, std::move(payload)});
      stream_cv_.notify_all();
    } else {
      pool_.ingest(env, std::move(payload), timeout_);
    }
  }

  int serve(uint16_t cmd_port);  // blocking accept loop

  std::atomic<bool> shutting_down{false};

 private:
  friend class EthFabric;

  // ---- dataplane executor (dma_mover pipeline parity) ----
  uint32_t execute_moves(const std::vector<Move>& moves, const CallCtx& c,
                         Communicator& comm) {
    for (const auto& m : moves) {
      std::vector<uint8_t> op0, op1;  // in uncompressed dtype
      BsSeg ps1;  // op1's raw packed segment when it arrived block-scaled
      uint32_t err;
      bool have0 = false, have1 = false;
      err = fetch(m.op0, m, c, comm, &op0, &have0);
      if (err) return err;
      err = fetch(m.op1, m, c, comm, &op1, &have1, &ps1);
      if (err) return err;
      std::vector<uint8_t>* result = nullptr;
      if (have0 && have1) {
        if (m.func < 0) return E_INVALID;
        if (ps1.valid) {
          // fused dequant->combine straight off the wire bytes
          // (quant.dequant_combine_packed twin): f32 accumulation,
          // bit-identical to dequantize-then-reduce in f32
          std::vector<float> a(m.count), r(m.count);
          bs_to_f32(op0, c.udtype, a);
          if (bsc_combine(m.func, ps1.qk, (ptrdiff_t)ps1.block,
                          ps1.scales(), ps1.q(), a.data(), r.data(),
                          (ptrdiff_t)m.count))
            return E_INVALID;
          op0 = bs_from_f32(r, c.udtype);
        } else {
          reduce_inplace(op0, op1, c.udtype, (uint8_t)m.func, m.count);
        }
        result = &op0;
      } else if (have0) {
        result = &op0;
      } else if (have1) {
        if (ps1.valid) {
          // plain packed recv: dequantize to the uncompressed dtype
          std::vector<float> f(m.count);
          bsc_dequant(ps1.qk, (ptrdiff_t)ps1.block, ps1.scales(), ps1.q(),
                      f.data(), (ptrdiff_t)m.count);
          op1 = bs_from_f32(f, c.udtype);
        }
        result = &op1;
      } else {
        return E_INVALID;
      }
      if (m.res_local) {
        if (m.res.mode == M_STREAM) {
          // RES_STREAM sink: result (uncompressed dtype) to the
          // external-kernel stream-out port
          std::lock_guard<std::mutex> lk(stream_mu_);
          stream_out_.emplace_back(c.udtype, *result);
          stream_cv_.notify_all();
        } else {
          uint8_t out_dt = m.res.compressed ? c.cdtype : c.udtype;
          auto out = convert(*result, c.udtype, out_dt, m.count);
          if (!mem_.write(m.res.addr, out.data(), out.size()))
            return E_INVALID;
        }
      }
      if (m.res_remote) {
        std::vector<uint8_t> wire;
        uint8_t wire_dt;
        if (m.eth_compressed && c.block_scaled()) {
          // block-scaled wire: requantize the result into one packed
          // [header | f32 scales | q] segment (quantize_packed twin) —
          // in-flight requantization at every reduce hop, like the
          // python tiers
          wire = bs_pack(*result, c.udtype, c.cdtype, c.qblock, m.count);
          wire_dt = c.cdtype;
          bs_encoded_segs_++;
        } else {
          wire_dt = m.eth_compressed ? c.cdtype : c.udtype;
          wire = convert(*result, c.udtype, wire_dt, m.count);
        }
        RankInfo& peer = comm.ranks[m.dst_rank];
        Envelope env;
        env.src = comm.my_global();
        env.dst = peer.global_rank;
        env.tag = m.tag;
        // stream deliveries bypass the rx pool and its seqn-ordered
        // channel (matches the Python executor)
        env.seqn = m.remote_stream ? 0 : peer.outbound_seq++;
        env.comm_id = comm.comm_id;
        env.strm = m.remote_stream ? 1 : 0;
        env.dtype = wire_dt;
        env.nbytes = wire.size();
        if (!eth_->send_msg(env, wire)) return E_INVALID;
      }
    }
    return E_OK;
  }

  uint32_t fetch(const Operand& o, const Move& m, const CallCtx& c,
                 Communicator& comm, std::vector<uint8_t>* out, bool* have,
                 BsSeg* ps = nullptr) {
    *have = false;
    if (o.mode == M_NONE) return E_OK;
    if (o.mode == M_IMM) {
      uint8_t stored = o.compressed ? c.cdtype : c.udtype;
      uint64_t nbytes = m.count * dtype_size(stored);
      if (!mem_.valid(o.addr, nbytes)) return E_INVALID;  // before alloc
      std::vector<uint8_t> raw(nbytes);
      if (!mem_.read(o.addr, raw.data(), raw.size()))
        return E_INVALID;  // raced with a free
      *out = convert(raw, stored, c.udtype, m.count);
      *have = true;
      return E_OK;
    }
    if (o.mode == M_ON_RECV) {
      RankInfo& peer = comm.ranks[o.src_rank];
      Envelope env;
      std::vector<uint8_t> payload;
      if (!pool_.seek(peer.global_rank, o.tag, peer.inbound_seq, comm.comm_id,
                      timeout_, &env, &payload))
        return E_RECV_TIMEOUT;
      peer.inbound_seq++;
      if (ps && m.eth_compressed && c.block_scaled()) {
        // self-describing packed segment: validated against its own
        // header AND the move's count (executor._fetch twin) — malformed
        // or mismatched segments are typed compression errors, and the
        // raw bytes stay packed for the caller's fused combine
        if (!bs_parse(std::move(payload), ps) || ps->count != m.count)
          return E_COMPRESSION;
        bs_decoded_segs_++;
        *have = true;
        return E_OK;
      }
      size_t n = env.nbytes / dtype_size(env.dtype);
      if (n != m.count) return E_DMA_MISMATCH;
      *out = convert(payload, env.dtype, c.udtype, m.count);
      *have = true;
      return E_OK;
    }
    if (o.mode == M_STREAM) {
      // continuous-stream semantics (AXIS parity, matches the Python
      // executor): WAIT until exactly m.count elements are available
      // across however many pushes/wire segments supplied them, THEN
      // consume — a timeout must not destroy partial data (a retry after
      // more pushes has to succeed, like the Python tiers)
      std::unique_lock<std::mutex> lk(stream_mu_);
      auto deadline = std::chrono::steady_clock::now() +
                      std::chrono::duration<double>(timeout_);
      auto dtfn = [](const std::pair<Envelope, std::vector<uint8_t>>& e) {
        return static_cast<uint8_t>(e.first.dtype);
      };
      while (stream_avail(stream_in_, stream_in_off_, dtfn) < m.count) {
        if (stream_cv_.wait_until(lk, deadline) == std::cv_status::timeout)
          return E_KRNL_TIMEOUT;
      }
      *out = stream_take(stream_in_, stream_in_off_, m.count, c.udtype,
                         dtfn);
      *have = true;
      return E_OK;
    }
    return E_INVALID;
  }

  // ---- continuous-stream helpers (caller holds stream_mu_) ----
  template <typename Q, typename DtFn>
  static size_t stream_avail(const Q& q, size_t off, DtFn dt) {
    size_t n = 0;
    for (size_t i = 0; i < q.size(); ++i) {
      size_t bytes = q[i].second.size() - (i == 0 ? off : 0);
      n += bytes / dtype_size(dt(q[i]));
    }
    return n;
  }

  template <typename Q, typename DtFn>
  static std::vector<uint8_t> stream_take(Q& q, size_t& off, uint64_t count,
                                          uint8_t out_dt, DtFn dt) {
    // consume exactly `count` elements across entries, converting each
    // entry from its own dtype; caller has verified availability
    std::vector<uint8_t> out;
    out.reserve(count * dtype_size(out_dt));
    uint64_t need = count;
    while (need && !q.empty()) {
      auto& head = q.front();
      uint8_t hdt = dt(head);
      size_t esz = dtype_size(hdt);
      size_t take = std::min<uint64_t>((head.second.size() - off) / esz,
                                       need);
      if (take == 0) {  // corrupt trailing bytes: drop the entry
        q.pop_front();
        off = 0;
        continue;
      }
      std::vector<uint8_t> raw(head.second.begin() + off,
                               head.second.begin() + off + take * esz);
      auto conv = hdt == out_dt ? raw : convert(raw, hdt, out_dt, take);
      out.insert(out.end(), conv.begin(), conv.end());
      need -= take;
      off += take * esz;
      if (off >= head.second.size()) {
        q.pop_front();
        off = 0;
      }
    }
    return out;
  }

  // ---- call queue (hostctrl async chaining parity) ----
  void call_worker() {
    for (;;) {
      std::pair<uint32_t, std::vector<uint8_t>> job;
      {
        std::unique_lock<std::mutex> lk(call_mu_);
        call_cv_.wait(lk, [this] {
          return !call_queue_.empty() || shutting_down.load();
        });
        if (shutting_down.load() && call_queue_.empty()) return;
        job = std::move(call_queue_.front());
        call_queue_.pop_front();
      }
      uint8_t scenario =
          job.second.empty() ? (uint8_t)OP_NOP : job.second[0];
      // waitfor error propagation (FIFO retirement means every wire
      // dependency already retired): a failed dependency fails this
      // call without executing it. Failed ids persist in a bounded map
      // past their MSG_WAIT (which erases call_status_), mirroring the
      // Python daemon.
      uint32_t err = E_OK;
      if (job.second.size() >= 54) {
        uint16_t nw = get_le<uint16_t>(job.second.data() + 52);
        size_t off = 54;
        std::lock_guard<std::mutex> lk(call_mu_);
        for (uint16_t i = 0; i < nw && off + 4 <= job.second.size();
             ++i, off += 4) {
          auto it = failed_calls_.find(
              get_le<uint32_t>(job.second.data() + off));
          if (it != failed_calls_.end()) { err = it->second; break; }
        }
      }
      std::string feature;
      if (err == E_OK) {
        try {
          err = run_call(job.second, &feature);
        } catch (const std::exception& e) {
          // a hostile/buggy descriptor (absurd count -> bad_alloc, ...)
          // must retire as an error, not terminate the daemon
          std::fprintf(stderr, "call %u failed: %s\n", job.first,
                       e.what());
          err = E_INVALID;
        }
        // only EXECUTED calls count (Python daemon parity): a call
        // skipped for a failed dependency must not skew per-call
        // profile averages
        if (profiling_ && scenario != OP_CONFIG) profiled_calls_++;
      }
      {
        std::lock_guard<std::mutex> lk(call_mu_);
        call_status_[job.first] = err;
        if (err != E_OK) {
          failed_calls_.emplace(job.first, err);
          // unsupported-feature names ride alongside the error word (a
          // strict subset of failed_calls_, aged out with it) so MSG_WAIT
          // can name the gap in the status-reply payload
          if (!feature.empty()) failed_feature_[job.first] = feature;
          while (failed_calls_.size() > 1024) {
            // remember the highest FAILED id the bounded FIFO ages out:
            // a deferred MSG_WAIT at/below this mark cannot tell
            // success from an evicted failure (see MSG_WAIT)
            uint32_t aged = failed_calls_.begin()->first;
            if (aged > failed_evicted_max_) failed_evicted_max_ = aged;
            failed_feature_.erase(aged);
            failed_calls_.erase(failed_calls_.begin());
          }
        }
        // Bound the status map (Python daemon parity): a chain client
        // waiting only the LAST id would otherwise leak one retired
        // entry per unwaited link forever. Entries a blocked MSG_WAIT
        // sleeps on are immune — evicting one would turn a retired
        // call into a spurious client timeout.
        if (call_status_.size() > 4096) {
          for (auto it = call_status_.begin();
               it != call_status_.end(); ++it) {
            if (wait_active_.find(it->first) == wait_active_.end()) {
              if (it->first > evicted_max_) evicted_max_ = it->first;
              call_status_.erase(it);
              break;
            }
          }
        }
        call_cv_.notify_all();
      }
    }
  }

  uint32_t run_call(const std::vector<uint8_t>& b, std::string* feature) {
    // layout matches protocol.pack_call (after the MSG_CALL byte)
    const uint8_t* p = b.data();
    uint8_t scenario = p[0], func = p[1], compression = p[2], stream = p[3];
    // p[7]: log2 of the scale-block size for C_BLOCK_SCALED calls
    // (0 = receiver default of 128, protocol.py pack_call); pad otherwise
    uint8_t udtype = p[4], cdtype = p[5], algorithm = p[6], qlog = p[7];
    uint64_t count = get_le<uint64_t>(p + 8);
    uint32_t comm_id = get_le<uint32_t>(p + 16);
    uint32_t root = get_le<uint32_t>(p + 20);
    uint32_t tag = get_le<uint32_t>(p + 24);
    uint64_t a0 = get_le<uint64_t>(p + 28);
    uint64_t a1 = get_le<uint64_t>(p + 36);
    uint64_t a2 = get_le<uint64_t>(p + 44);
    if (scenario == OP_NOP) return E_OK;
    if (scenario == OP_CONFIG) return handle_config(tag, count);
    Communicator* comm;
    {
      std::lock_guard<std::mutex> lk(comm_mu_);
      auto it = comms_.find(comm_id);
      if (it == comms_.end()) return E_COMM_NOT_CONFIGURED;
      comm = &it->second;
    }
    // sanity bound BEFORE expansion: a hostile count would otherwise
    // materialize count/segment move objects. Barrier is exempt — its
    // expansion normalizes every data-movement field to a 1-element
    // rendezvous, so barrier semantics stay descriptor-invariant
    // (matches the Python daemon's rewrite-then-bound ordering)
    if (scenario != OP_BARRIER &&
        count > MAX_CALL_BYTES / dtype_size(udtype))
      return E_DMA_SIZE;
    uint32_t qblock = 0;
    if (compression & C_BLOCK_SCALED) {
      // clamp to the python quant.clamp_block envelope: pow2 in [32, 4096]
      qblock = qlog ? (qlog >= 12 ? 4096u : (1u << qlog)) : 128u;
      if (qblock < 32) qblock = 32;
    }
    CallCtx c{comm->size(), comm->local_rank, udtype, cdtype, max_seg_,
              compression, stream, qblock};
    std::vector<Move> moves;
    uint32_t err = expand(moves, c, scenario, func, count, root, tag, a0, a1,
                          a2, algorithm, feature);
    if (err) return err;
    return execute_moves(moves, c, *comm);
  }

  // ---- runtime config calls (ACCL_CONFIG parity, c:1240-1283) ----
  // subfunction in tag, value in count (ms for timeout, bytes for segment
  // size, StackType code for stack select)
  uint32_t handle_config(uint32_t fn, uint64_t val) {
    switch (fn) {
      case CFG_RESET:
        soft_reset();
        return E_OK;
      case CFG_ENABLE_PKT:
        pkt_enabled_ = true;
        return E_OK;
      case CFG_SET_TIMEOUT:
        // same clamp as MSG_SET_TIMEOUT: this field feeds wait deadlines
        timeout_ = sane_budget(static_cast<double>(val) / 1000.0, true);
        return E_OK;
      case CFG_SET_SEG:
        if (val > bufsize_) return E_DMA_SIZE;
        max_seg_ = static_cast<size_t>(val);
        return E_OK;
      case CFG_OPEN_PORT:
        return eth_->listening() ? E_OK : E_OPEN_PORT;
      case CFG_OPEN_CON:
        return eth_->connect_all();
      case CFG_CLOSE_CON:
        eth_->disconnect_all();
        return E_OK;
      case CFG_SET_STACK:
        if (val > 1) return E_INVALID;  // 0=tcp, 1=udp (StackType parity)
        return set_stack(val == 1);
      case CFG_START_PROF:
        profiling_ = true;
        return E_OK;
      case CFG_END_PROF:
        profiling_ = false;
        return E_OK;
      default:
        return E_INVALID;
    }
  }

  bool rebind_fabric(bool udp, uint16_t port) {
    // retry briefly: the kernel may take a moment to release the port
    for (int i = 0; i < 50; ++i) {
      auto fab = std::make_unique<EthFabric>(rank_, port, this, udp);
      if (fab->ok()) {
        eth_ = std::move(fab);
        return true;
      }
      usleep(50 * 1000);
    }
    return false;
  }

  void relearn_peers() {
    std::lock_guard<std::mutex> lk(comm_mu_);
    for (auto& kv : comms_)
      for (auto& r : kv.second.ranks)
        if (r.global_rank != rank_ && r.cmd_port)
          eth_->learn_peer(r.global_rank, r.host,
                           static_cast<uint16_t>(r.cmd_port + world_));
  }

  uint32_t set_stack(bool udp) {
    // HOUSEKEEP_SET_STACK_TYPE parity (c:1270-1272): quiesced-only swap;
    // in-flight eth traffic on the old fabric is lost and every rank must
    // switch before new traffic flows.
    if (udp == eth_->is_udp()) return E_OK;
    bool old_udp = eth_->is_udp();
    uint16_t port = static_cast<uint16_t>(port_base_ + world_ + rank_);
    // hold eth_mu_ for the whole swap so a concurrent conn thread can
    // never observe (or call into) the half-destroyed old fabric
    std::lock_guard<std::mutex> elk(eth_mu_);
    eth_->stop();  // joins fabric threads; port becomes rebindable
    if (rebind_fabric(udp, port)) {
      relearn_peers();
      return E_OK;
    }
    // keep a working fabric: fall back to the old stack type rather than
    // leaving the daemon wired to a stopped fabric
    if (rebind_fabric(old_udp, port)) relearn_peers();
    return E_OPEN_PORT;
  }

  void soft_reset() {
    pool_.reset();
    {
      // retx rings/trackers restart with the seqn spaces (vs stack swap)
      std::lock_guard<std::mutex> elk(eth_mu_);
      eth_->reset_retx();
    }
    {
      // drain stream ports: stale cross-epoch stream data must not leak
      std::lock_guard<std::mutex> lk(stream_mu_);
      stream_in_.clear();
      stream_out_.clear();
      stream_in_off_ = stream_out_off_ = 0;
    }
    std::lock_guard<std::mutex> lk(comm_mu_);
    for (auto& kv : comms_)
      for (auto& r : kv.second.ranks) r.inbound_seq = r.outbound_seq = 0;
  }

  // ---- command connection ----
  void serve_conn(int fd);
  std::vector<uint8_t> handle(const std::vector<uint8_t>& body,
                              uint32_t* last_call_id = nullptr);

  uint32_t rank_, world_;
  uint16_t port_base_;
  DeviceMemory mem_;
  RxBufferPool pool_;
  // max_seg_/timeout_ (and the config flags below) are written by both the
  // call worker (ACCL_CONFIG subfunctions) and command-connection threads
  // (MSG_SET_*), and read by GET_INFO from yet other connection threads —
  // atomics keep that read torn-/race-free without a config mutex
  size_t bufsize_, nbufs_;
  std::atomic<size_t> max_seg_;
  std::atomic<double> timeout_{30.0};
  std::map<uint32_t, Communicator> comms_;
  std::mutex comm_mu_;
  // unique_ptr so a runtime stack-type config call can swap the fabric.
  // eth_mu_ serializes the swap (call-worker thread) against command
  // connection threads that dereference eth_ (GET_INFO, comm config,
  // shutdown); the call worker's own data path needs no lock — it is the
  // only thread that reassigns the pointer.
  std::unique_ptr<EthFabric> eth_;
  std::mutex eth_mu_;
  // runtime config-call state (ACCL_CONFIG parity): pkt engines are
  // default-armed; profiling counters are in-daemon
  std::atomic<bool> pkt_enabled_{true};
  std::atomic<bool> profiling_{false};
  std::atomic<uint32_t> profiled_calls_{0};
  // stream ports (external-kernel AXIS analog): in = OP0_STREAM source,
  // out = RES_STREAM sink; both host-accessible via MSG_STREAM_PUSH/POP.
  // Continuous-stream semantics: consumers read element counts across
  // entry boundaries via the head offsets (bytes into the front entry).
  std::deque<std::pair<Envelope, std::vector<uint8_t>>> stream_in_;
  size_t stream_in_off_ = 0;
  std::deque<std::pair<uint8_t, std::vector<uint8_t>>> stream_out_;
  size_t stream_out_off_ = 0;
  std::mutex stream_mu_;
  std::condition_variable stream_cv_;
  // calls
  std::deque<std::pair<uint32_t, std::vector<uint8_t>>> call_queue_;
  std::map<uint32_t, uint32_t> call_status_;
  // ids a blocked MSG_WAIT sleeps on (waiter counts): immune to the
  // status-map eviction (guarded by call_mu_)
  std::map<uint32_t, int> wait_active_;
  // highest retired-status id the eviction dropped: MSG_WAIT resolves
  // ids at/below it from failed_calls_ (retirement is FIFO)
  uint32_t evicted_max_ = 0;
  std::map<uint32_t, uint32_t> failed_calls_;  // persists past MSG_WAIT
  // unsupported-feature names for failed calls (guarded by call_mu_;
  // strict subset of failed_calls_, evicted with it)
  std::map<uint32_t, std::string> failed_feature_;
  uint32_t failed_evicted_max_ = 0;  // highest failure aged out of it
  uint32_t next_call_id_ = 1;
  std::mutex call_mu_;
  std::condition_variable call_cv_;
  std::thread worker_;
  std::vector<std::thread> conn_threads_;
  // failed-call reply with the feature name riding after the error word
  // (old drivers slice reply[1:5] and never see it); caller holds call_mu_
  std::vector<uint8_t> fail_reply(uint32_t id, uint32_t err) {
    auto it = failed_feature_.find(id);
    return it == failed_feature_.end()
               ? status_reply(err)
               : status_reply(err, it->second.c_str());
  }
  // native observability counters (surfaced as text lines in the
  // MSG_DUMP_RX reply; the chaos harness asserts ENGAGEMENT on them).
  // They live on the daemon, not the fabric, so a runtime stack swap
  // cannot zero them mid-experiment.
  std::atomic<uint64_t> retx_tracked_{0}, retx_retransmits_{0},
      retx_rto_fires_{0}, retx_fast_retransmits_{0}, retx_acked_{0},
      retx_dedup_dropped_{0}, retx_horizon_dropped_{0}, retx_gave_up_{0},
      retx_window_stalls_{0}, retx_acks_sent_{0};
  std::atomic<uint64_t> integrity_failed_{0};
  std::atomic<uint64_t> bs_encoded_segs_{0}, bs_decoded_segs_{0};
};

// ---- EthFabric impl -------------------------------------------------------
// returns -1 on bind failure (caller decides whether that is fatal —
// startup exits, a runtime stack swap retries and reports an error word)
static int make_server(uint16_t port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(port);
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof addr) < 0) {
    ::close(fd);
    return -1;
  }
  listen(fd, 16);
  return fd;
}

static int make_udp_server(uint16_t port) {
  int fd = ::socket(AF_INET, SOCK_DGRAM, 0);
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  int buf = 8 << 20;
  setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &buf, sizeof buf);
  setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &buf, sizeof buf);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(port);
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof addr) < 0) {
    ::close(fd);
    return -1;
  }
  return fd;
}

// monotonic seconds (retx deadlines; matches time.monotonic usage in the
// python reliability endpoint)
static double mono_now() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// splitmix64 finalizer: deterministic retransmission jitter (the python
// endpoint's _mix analog — desynchronizes RTO herds without an RNG)
static uint64_t mix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ull;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBull;
  return x ^ (x >> 31);
}

EthFabric::EthFabric(uint32_t me, uint16_t listen_port, RankDaemon* daemon,
                     bool udp)
    : me_(me), daemon_(daemon), udp_(udp),
      csum_(csum_enabled_from_env()),
      retx_window_(retx_window_from_env()) {
  if (const char* v = getenv("ACCL_TPU_CHAOS_TX_DROP"))
    chaos_drop_every_ = atoi(v);
  if (const char* v = getenv("ACCL_TPU_CHAOS_TX_CORRUPT"))
    chaos_corrupt_every_ = atoi(v);
  listen_fd_ = udp_ ? make_udp_server(listen_port) : make_server(listen_port);
  if (listen_fd_ < 0) {
    stopping_.store(true);  // never usable; stop()/dtor are no-ops
    return;
  }
  if (udp_) {
    threads_.emplace_back([this] { udp_recv_loop(); });
    // RTO scan thread: only the UDP stack retransmits (TCP recovers in
    // the kernel); a zero window means nothing is ever tracked
    if (retx_window_ > 0)
      threads_.emplace_back([this] { retx_tick_loop(); });
  } else {
    threads_.emplace_back([this] { accept_loop(); });
  }
}

EthFabric::~EthFabric() { stop(); }

void EthFabric::stop() {
  if (stopping_.exchange(true)) return;
  retx_space_.notify_all();  // unblock window-stalled senders
  ::shutdown(listen_fd_, SHUT_RDWR);
  ::close(listen_fd_);
  {
    std::lock_guard<std::mutex> lk(mu_);
    for (auto& kv : peers_) ::close(kv.second);
    // unblock inbound recv threads too: they reference this fabric, so
    // they must exit before the object may be destroyed (stack swap)
    for (int fd : inbound_fds_) ::shutdown(fd, SHUT_RDWR);
    for (auto& kv : dqs_) {
      {
        std::lock_guard<std::mutex> qlk(kv.second->mu);
        kv.second->stop = true;
      }
      kv.second->cv.notify_all();
    }
  }
  // join ALL owned threads (accept/udp loop, delivery workers, inbound
  // recv) so the fabric is destructible — a runtime stack swap replaces
  // the object, and a surviving thread would use it after free. The
  // index loop re-checks size under mu_ because accept_loop may append
  // one final entry while draining.
  for (size_t i = 0;; ++i) {
    std::thread t;
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (i >= threads_.size()) break;
      t = std::move(threads_[i]);
    }
    if (t.joinable()) t.join();
  }
}

std::vector<uint8_t> EthFabric::encode_eth(
    const Envelope& env, const std::vector<uint8_t>& payload,
    bool with_msg_byte) {
  std::vector<uint8_t> body;
  if (with_msg_byte) body.push_back(MSG_ETH);
  put_le<uint32_t>(body, env.src);
  put_le<uint32_t>(body, env.dst);
  put_le<uint32_t>(body, env.tag);
  put_le<uint32_t>(body, env.seqn);
  put_le<uint32_t>(body, env.comm_id);
  body.push_back(env.strm);
  body.push_back(env.dtype);
  put_le<uint64_t>(body, env.nbytes);
  body.insert(body.end(), payload.begin(), payload.end());
  // trailing integrity word: after the payload, outside the header's
  // nbytes — decoders predating the field never see it (protocol.py)
  if (env.has_csum) put_le<uint32_t>(body, env.csum);
  return body;
}

bool EthFabric::decode_eth(const uint8_t* p, size_t len, Envelope& env,
                           std::vector<uint8_t>& payload) {
  if (len < 30) return false;
  env.src = get_le<uint32_t>(p);
  env.dst = get_le<uint32_t>(p + 4);
  env.tag = get_le<uint32_t>(p + 8);
  env.seqn = get_le<uint32_t>(p + 12);
  env.comm_id = get_le<uint32_t>(p + 16);
  env.strm = p[20];
  env.dtype = p[21];
  env.nbytes = get_le<uint64_t>(p + 22);
  // Slice the payload by the header's nbytes, NOT the frame length: the
  // trailing integrity word (when the sender appended one) lives after
  // the payload, outside nbytes, so decoders predating the field never
  // take it as payload bytes (protocol.py unpack_eth twin).
  if (env.nbytes > len - 30) return false;  // truncated frame
  payload.assign(p + 30, p + 30 + env.nbytes);
  if (len - 30 >= env.nbytes + 4) {
    env.csum = get_le<uint32_t>(p + 30 + env.nbytes);
    env.has_csum = true;
  }
  return true;
}

// ---- udp packetizer/reassembly (udp_packetizer + rxbuf_session parity) ----
void EthFabric::udp_recv_loop() {
  std::vector<uint8_t> dgram(kMaxPkt + 12 + 64);
  for (;;) {
    ssize_t n = ::recvfrom(listen_fd_, dgram.data(), dgram.size(), 0,
                           nullptr, nullptr);
    if (n < 0) {
      if (errno == EINTR) continue;  // signal must not kill the fabric
      return;                        // socket closed
    }
    if (static_cast<size_t>(n) < 12) continue;
    udp_handle(dgram.data(), static_cast<size_t>(n));
  }
}

void EthFabric::udp_handle(const uint8_t* dgram, size_t len) {
  uint32_t sender = get_le<uint32_t>(dgram);
  uint32_t msg_id = get_le<uint32_t>(dgram + 4);
  uint16_t idx = get_le<uint16_t>(dgram + 8);
  uint16_t nfrags = get_le<uint16_t>(dgram + 10);
  if (nfrags == 0) return;
  double now = std::chrono::duration<double>(
      std::chrono::steady_clock::now().time_since_epoch()).count();
  auto key = std::make_pair(sender, msg_id);
  auto& part = partial_[key];
  if (part.frags.empty()) {
    part.deadline = now + kPartialTtl;
    part.nfrags = nfrags;
  }
  part.frags[idx].assign(dgram + 12, dgram + len);
  if (part.frags.size() == part.nfrags) {
    std::vector<uint8_t> frame;
    for (auto& kv : part.frags)
      frame.insert(frame.end(), kv.second.begin(), kv.second.end());
    partial_.erase(key);
    Envelope env;
    std::vector<uint8_t> payload;
    if (decode_eth(frame.data(), frame.size(), env, payload))
      deliver(env.src, std::move(env), std::move(payload));
  }
  // GC stale partials (lost fragments must not leak)
  for (auto it = partial_.begin(); it != partial_.end();) {
    if (it->second.deadline < now) it = partial_.erase(it);
    else ++it;
  }
}

void EthFabric::deliver(uint32_t sender, Envelope&& env,
                        std::vector<uint8_t>&& payload) {
  // ACK control lane: consumed here, never reaches the rx pool
  if (env.strm == ACK_STRM) {
    uint32_t cum;
    std::vector<uint32_t> sel;
    if (retx_window_ > 0 &&
        unpack_ack(payload.data(), payload.size(), &cum, &sel))
      on_ack(env.src, env.comm_id, cum, sel);
    return;
  }
  // landing integrity check, BEFORE the freshness check (corrupt-as-loss,
  // daemon._verify_frame twin): the tracker must never record a corrupt
  // frame's seqn — it would dedup-drop the retransmission of the
  // original. The frame stays unacked, so the sender's RTO re-fetches it.
  if (csum_ && env.has_csum && env.strm <= 1 &&
      crc32c(payload.data(), payload.size()) != env.csum) {
    daemon_->integrity_failed_++;
    return;
  }
  // receiver freshness tracker (RetxEndpoint.fresh twin); stream frames
  // (strm=1) bypass seqn ordering like the python endpoint
  bool tracked = retx_window_ > 0 && env.strm == 0;
  if (tracked) {
    auto key = std::make_pair(env.src, env.comm_id);
    uint32_t ack_cum = 0;
    bool dup = false;
    {
      std::lock_guard<std::mutex> lk(retx_mu_);
      auto& st = rcv_[key];
      if (env.seqn >= st.first + kSeqnHorizon) {
        // far-future frame: dropped UNACKED (a hostile/raced seqn must
        // not inflate the ooo set; the sender's RTO recovers real ones)
        daemon_->retx_horizon_dropped_++;
        return;
      }
      if (env.seqn < st.first || st.second.count(env.seqn)) {
        daemon_->retx_dedup_dropped_++;
        dup = true;
        ack_cum = st.first;
      }
    }
    if (dup) {
      // duplicate: re-ack cumulative state (the original ack was lost)
      send_ack(env.src, env.comm_id, ack_cum, {});
      return;
    }
  }
  DeliverQ* dq;
  {
    std::lock_guard<std::mutex> lk(mu_);
    auto& slot = dqs_[sender];
    if (!slot) {
      slot = std::make_unique<DeliverQ>();
      DeliverQ* p = slot.get();
      threads_.emplace_back([this, p] {
        for (;;) {
          std::pair<Envelope, std::vector<uint8_t>> item;
          {
            std::unique_lock<std::mutex> qlk(p->mu);
            p->cv.wait(qlk, [p] { return p->stop || !p->q.empty(); });
            if (p->stop && p->q.empty()) return;
            item = std::move(p->q.front());
            p->q.pop_front();
          }
          daemon_->ingest(item.first, std::move(item.second));
        }
      });
    }
    dq = slot.get();
  }
  {
    std::lock_guard<std::mutex> qlk(dq->mu);
    // bounded queue: DROP beyond the depth limit (UDP semantics — no
    // flow control here; unbounded growth would exhaust memory while the
    // rx pool is full). Dropped frames stay UNACKED so a retransmitting
    // sender recovers them; otherwise they surface as receive timeouts.
    if (dq->q.size() >= kQueueDepth) return;
    dq->q.emplace_back(env, std::move(payload));
  }
  dq->cv.notify_one();
  if (tracked) {
    // acknowledge only what was actually enqueued (RetxEndpoint.record
    // twin): advance cum / absorb out-of-order, then cum+selective ack
    uint32_t cum;
    std::vector<uint32_t> sel;
    auto key = std::make_pair(env.src, env.comm_id);
    {
      std::lock_guard<std::mutex> lk(retx_mu_);
      auto& st = rcv_[key];
      if (env.seqn == st.first) {
        st.first++;
        while (st.second.count(st.first)) {
          st.second.erase(st.first);
          st.first++;
        }
      } else if (env.seqn > st.first) {
        st.second.insert(env.seqn);
      }
      cum = st.first;
      sel.assign(st.second.begin(), st.second.end());
    }
    send_ack(env.src, env.comm_id, cum, sel);
  }
}

void EthFabric::accept_loop() {
  for (;;) {
    int fd = ::accept(listen_fd_, nullptr, nullptr);
    if (fd < 0) return;
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    // tracked, not detached: stop() must be able to shut these down and
    // join them before the fabric is destroyed (runtime stack swap)
    std::lock_guard<std::mutex> lk(mu_);
    if (stopping_.load()) {
      ::close(fd);
      return;
    }
    inbound_fds_.push_back(fd);
    threads_.emplace_back([this, fd] { recv_loop(fd); });
  }
}

void EthFabric::recv_loop(int fd) {
  std::vector<uint8_t> body;
  while (recv_frame(fd, body)) {
    if (body.empty() || body[0] != MSG_ETH) continue;
    Envelope env;
    std::vector<uint8_t> payload;
    if (decode_eth(body.data() + 1, body.size() - 1, env, payload)) {
      // landing integrity check (corrupt-as-loss; no retx on TCP — the
      // kernel already guarantees delivery, this guards the app layer)
      if (csum_ && env.has_csum && env.strm <= 1 &&
          crc32c(payload.data(), payload.size()) != env.csum) {
        daemon_->integrity_failed_++;
        continue;
      }
      daemon_->ingest(env, std::move(payload));
    }
  }
  // deregister BEFORE closing: once closed the fd number may be reused by
  // the kernel, and a later stop() must not shutdown an unrelated socket
  {
    std::lock_guard<std::mutex> lk(mu_);
    inbound_fds_.erase(
        std::remove(inbound_fds_.begin(), inbound_fds_.end(), fd),
        inbound_fds_.end());
  }
  ::close(fd);
}

// fragment at kMaxPkt with the shared 12B header and sendto each piece;
// frame excludes the MSG_ETH type byte (datagram boundaries replace
// stream framing). Shared by fresh sends, ACKs, and retransmissions —
// a resend re-fragments the stored frame under a fresh msg_id.
bool EthFabric::udp_send_frame(uint32_t dst,
                               const std::vector<uint8_t>& frame) {
  // TX chaos (mixed-world sweep knobs, see member comment): applied to
  // strm=0 data frames only, on a COPY for corruption so the in-flight
  // ring always retains the intact original for the RTO resend
  const std::vector<uint8_t>* out = &frame;
  std::vector<uint8_t> mangled;
  if ((chaos_drop_every_ || chaos_corrupt_every_) && frame.size() >= 30 &&
      frame[20] == 0) {
    uint64_t n = ++chaos_tx_n_;
    if (chaos_drop_every_ && n % chaos_drop_every_ == 0)
      return true;  // vanished on the wire; the RTO scan re-fires it
    if (chaos_corrupt_every_ && n % chaos_corrupt_every_ == 0) {
      uint64_t nb = get_le<uint64_t>(frame.data() + 22);
      if (nb > 0 && 30 + nb <= frame.size()) {
        mangled = frame;
        mangled[30 + nb / 2] ^= 0x10;  // header intact: the receiver's
        out = &mangled;                // csum verify treats it as loss
      }
    }
  }
  sockaddr_in addr{};
  uint32_t msg_id;
  {
    std::lock_guard<std::mutex> lk(mu_);
    auto ait = peer_addrs_.find(dst);
    if (ait == peer_addrs_.end()) return false;
    addr.sin_family = AF_INET;
    addr.sin_port = htons(ait->second.second);
    inet_pton(AF_INET, ait->second.first.c_str(), &addr.sin_addr);
    msg_id = next_msg_id_++;
  }
  size_t nfrags = out->empty() ? 1 : (out->size() + kMaxPkt - 1) / kMaxPkt;
  for (size_t i = 0; i < nfrags; ++i) {
    std::vector<uint8_t> pkt;
    put_le<uint32_t>(pkt, me_);
    put_le<uint32_t>(pkt, msg_id);
    put_le<uint16_t>(pkt, static_cast<uint16_t>(i));
    put_le<uint16_t>(pkt, static_cast<uint16_t>(nfrags));
    size_t lo = i * kMaxPkt;
    size_t hi = std::min(out->size(), lo + kMaxPkt);
    pkt.insert(pkt.end(), out->begin() + lo, out->begin() + hi);
    if (::sendto(listen_fd_, pkt.data(), pkt.size(), 0,
                 reinterpret_cast<sockaddr*>(&addr), sizeof addr) < 0)
      return false;
  }
  return true;
}

// sender in-flight tracking (RetxEndpoint.track twin): bounded per-channel
// window with a soft cap — a stall-timeout tracks anyway rather than
// wedging the call worker forever on a dead peer
void EthFabric::track(const Envelope& env, const std::vector<uint8_t>& frame) {
  auto key = std::make_pair(env.dst, env.comm_id);
  std::unique_lock<std::mutex> lk(retx_mu_);
  auto full = [&] {
    auto it = ring_.find(key);
    return it != ring_.end() &&
           (int)it->second.size() >= retx_window_;
  };
  if (full()) {
    daemon_->retx_window_stalls_++;
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::duration<double>(kRtoMaxS * 4);
    retx_space_.wait_until(lk, deadline,
                           [&] { return !full() || stopping_.load(); });
  }
  Flight fl;
  fl.frame = frame;
  fl.t0 = mono_now();
  fl.deadline = fl.t0 + cur_rto_locked();
  ring_[key][env.seqn] = std::move(fl);
  inflight_++;
  daemon_->retx_tracked_++;
}

double EthFabric::cur_rto_locked() const {
  if (srtt_ < 0.0) return kRtoS;
  double rto = srtt_ + 4.0 * rttvar_;
  return rto < kRtoMinS ? kRtoMinS : (rto > kRtoMaxS ? kRtoMaxS : rto);
}

double EthFabric::rto_of_locked(int tries, uint32_t dst, uint32_t comm_id,
                                uint32_t seqn) const {
  double rto = cur_rto_locked() * (double)(1u << (tries > 10 ? 10 : tries));
  if (rto > kRtoMaxS) rto = kRtoMaxS;
  uint64_t h = mix64(mix64(((uint64_t)dst << 32) | comm_id) ^
                     (((uint64_t)tries << 32) | seqn));
  return rto * (0.75 + 0.5 * (double)(h >> 11) / 9007199254740992.0);
}

void EthFabric::note_rtt_locked(const Flight& fl) {
  if (fl.tries) return;  // Karn's rule: retransmitted samples are ambiguous
  double rtt = mono_now() - fl.t0;
  if (srtt_ < 0.0) {
    srtt_ = rtt;
    rttvar_ = rtt / 2.0;
  } else {
    double d = srtt_ - rtt;
    rttvar_ += 0.25 * ((d < 0 ? -d : d) - rttvar_);
    srtt_ += 0.125 * (rtt - srtt_);
  }
}

// RetxEndpoint.on_ack twin: free everything below cum plus the selective
// set (RTT samples per Karn), then one-shot fast-retransmit the gap
// below the highest selective ack — resends happen OUTSIDE the lock
void EthFabric::on_ack(uint32_t src, uint32_t comm_id, uint32_t cum,
                       const std::vector<uint32_t>& sel) {
  auto key = std::make_pair(src, comm_id);
  std::vector<std::vector<uint8_t>> resend;
  {
    std::lock_guard<std::mutex> lk(retx_mu_);
    auto it = ring_.find(key);
    if (it == ring_.end()) return;
    auto& chan = it->second;
    size_t freed = 0;
    for (auto fit = chan.begin(); fit != chan.end() && fit->first < cum;) {
      note_rtt_locked(fit->second);
      fit = chan.erase(fit);
      freed++;
    }
    for (uint32_t s : sel) {
      auto fit = chan.find(s);
      if (fit != chan.end()) {
        note_rtt_locked(fit->second);
        chan.erase(fit);
        freed++;
      }
    }
    if (!sel.empty() && !chan.empty()) {
      uint32_t gap_hi = *std::max_element(sel.begin(), sel.end());
      double now = mono_now();
      for (auto& kv : chan) {
        if (kv.first < gap_hi && !kv.second.fast) {
          kv.second.fast = true;
          kv.second.tries++;
          kv.second.deadline =
              now + rto_of_locked(kv.second.tries, src, comm_id, kv.first);
          resend.push_back(kv.second.frame);
        }
      }
    }
    if (freed) {
      inflight_ -= freed;
      daemon_->retx_acked_ += freed;
      retx_space_.notify_all();
    }
    if (chan.empty()) ring_.erase(it);
  }
  for (auto& f : resend) {
    daemon_->retx_retransmits_++;
    daemon_->retx_fast_retransmits_++;
    udp_send_frame(src, f);
  }
}

void EthFabric::send_ack(uint32_t dst, uint32_t comm_id, uint32_t cum,
                         const std::vector<uint32_t>& sel) {
  // acks are never checksummed, tracked, or counted as data — recovery
  // must not turn against itself (daemon._send_ack twin)
  std::vector<uint8_t> payload = pack_ack(cum, sel);
  Envelope env{};
  env.src = me_;
  env.dst = dst;
  env.tag = 0;
  env.seqn = cum;
  env.comm_id = comm_id;
  env.strm = ACK_STRM;
  env.dtype = DT_U8;
  env.nbytes = payload.size();
  daemon_->retx_acks_sent_++;
  udp_send_frame(dst, encode_eth(env, payload, false));
}

// ~10ms RTO scan (the python endpoint's reaper cadence): expired flights
// retransmit with exponential backoff until the try budget gives up
void EthFabric::retx_tick_loop() {
  while (!stopping_.load()) {
    usleep(10 * 1000);
    std::vector<std::pair<uint32_t, std::vector<uint8_t>>> resend;
    double now = mono_now();
    {
      std::lock_guard<std::mutex> lk(retx_mu_);
      if (!inflight_) continue;
      size_t freed = 0;
      for (auto it = ring_.begin(); it != ring_.end();) {
        auto& chan = it->second;
        for (auto fit = chan.begin(); fit != chan.end();) {
          Flight& fl = fit->second;
          if (fl.deadline > now) {
            ++fit;
            continue;
          }
          if (fl.tries >= kMaxTries) {
            daemon_->retx_gave_up_++;
            inflight_--;
            freed++;
            fit = chan.erase(fit);
            continue;
          }
          fl.tries++;
          fl.deadline = now + rto_of_locked(fl.tries, it->first.first,
                                            it->first.second, fit->first);
          resend.emplace_back(it->first.first, fl.frame);
          ++fit;
        }
        if (chan.empty()) it = ring_.erase(it);
        else ++it;
      }
      if (freed) retx_space_.notify_all();
    }
    for (auto& r : resend) {
      daemon_->retx_retransmits_++;
      daemon_->retx_rto_fires_++;
      udp_send_frame(r.first, r.second);
    }
  }
}

void EthFabric::reset_retx() {
  std::lock_guard<std::mutex> lk(retx_mu_);
  ring_.clear();
  rcv_.clear();
  inflight_ = 0;
  srtt_ = -1.0;
  rttvar_ = 0.0;
  retx_space_.notify_all();
}

bool EthFabric::send_msg(const Envelope& env,
                         const std::vector<uint8_t>& payload) {
  // data/stream frames get the trailing integrity word when checksums
  // are enabled; computed BEFORE tracking so the in-flight ring stores
  // the verified frame and a retransmission carries the same word
  Envelope e = env;
  if (csum_ && e.strm <= 1 && !payload.empty()) {
    e.csum = crc32c(payload.data(), payload.size());
    e.has_csum = true;
  }
  if (udp_) {
    std::vector<uint8_t> frame = encode_eth(e, payload, false);
    if (retx_window_ > 0 && e.strm == 0) track(e, frame);
    return udp_send_frame(e.dst, frame);
  }
  int fd;
  std::mutex* peer_mu;
  {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = peers_.find(e.dst);
    if (it == peers_.end()) {
      auto ait = peer_addrs_.find(e.dst);
      if (ait == peer_addrs_.end()) return false;
      fd = ::socket(AF_INET, SOCK_STREAM, 0);
      sockaddr_in addr{};
      addr.sin_family = AF_INET;
      addr.sin_port = htons(ait->second.second);
      inet_pton(AF_INET, ait->second.first.c_str(), &addr.sin_addr);
      if (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof addr) < 0) {
        ::close(fd);
        return false;
      }
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
      peers_[e.dst] = fd;
      peer_mus_[e.dst] = std::make_unique<std::mutex>();
    } else {
      fd = it->second;
    }
    peer_mu = peer_mus_[e.dst].get();
  }
  std::lock_guard<std::mutex> plk(*peer_mu);
  std::vector<uint8_t> body = encode_eth(e, payload, true);
  return send_frame(fd, body);
}

uint32_t EthFabric::connect_all() {
  // openCon parity (ccl_offload_control.c:109-165): eagerly open a session
  // to every known peer, replacing the lazy per-send dial. UDP is
  // connectionless (the reference's VNx path programs a socket table
  // instead), so there is nothing to open.
  if (udp_) return E_OK;
  std::vector<std::pair<uint32_t, std::pair<std::string, uint16_t>>> targets;
  {
    std::lock_guard<std::mutex> lk(mu_);
    for (auto& kv : peer_addrs_)
      if (!peers_.count(kv.first)) targets.push_back(kv);
  }
  uint32_t err = E_OK;
  for (auto& t : targets) {
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(t.second.second);
    inet_pton(AF_INET, t.second.first.c_str(), &addr.sin_addr);
    if (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof addr) < 0) {
      ::close(fd);
      err |= E_OPEN_CON;
      continue;
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    std::lock_guard<std::mutex> lk(mu_);
    if (peers_.count(t.first)) {  // lost a dial race with send_msg
      ::close(fd);
    } else {
      peers_[t.first] = fd;
      peer_mus_[t.first] = std::make_unique<std::mutex>();
    }
  }
  return err;
}

void EthFabric::disconnect_all() {
  // Only safe from the call worker (the sole sender on this rank), so no
  // send can hold a per-peer mutex we are about to destroy.
  if (udp_) return;
  std::lock_guard<std::mutex> lk(mu_);
  for (auto& kv : peers_) ::close(kv.second);
  peers_.clear();
  peer_mus_.clear();
}

// ---- command server -------------------------------------------------------
int RankDaemon::serve(uint16_t cmd_port) {
  int server = make_server(cmd_port);
  if (server < 0) {
    fprintf(stderr, "rank %u: cmd port %u bind failed\n", rank_, cmd_port);
    return 1;
  }
  printf("native rank %u/%u serving cmd=%u eth=%u\n", rank_, world_, cmd_port,
         port_base_ + world_ + rank_);
  fflush(stdout);
  while (!shutting_down.load()) {
    int fd = ::accept(server, nullptr, nullptr);
    if (fd < 0) break;
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    conn_threads_.emplace_back([this, fd] { serve_conn(fd); });
  }
  ::close(server);
  return 0;
}

void RankDaemon::serve_conn(int fd) {
  // Buffered request parsing + coalesced replies (mirror of the Python
  // daemon's _serve_conn): a pipelined client batch ([pushes, CALL,
  // WAIT, READ]) lands in one recv, every frame is handled back to
  // back, and the replies leave in one send — instead of two recv
  // syscalls per frame and a write per reply.
  // Frames/replies past kBig bypass the coalescing buffers: big payloads
  // recv directly into the frame buffer (no 64K chunking through rbuf)
  // and reply via the scatter-gather send_frame (no extra full-size
  // copy); a malformed frame flushes buffered replies before dropping
  // the connection so earlier valid requests keep their answers.
  constexpr size_t kBig = 1 << 20;
  std::vector<uint8_t> rbuf, replies, body;
  // per-connection state: the id of the last MSG_CALL this connection
  // submitted (the MSG_WAIT WAIT_LAST sentinel, protocol.py)
  uint32_t last_call_id = 0;
  uint8_t chunk[1 << 16];
  auto flush = [&]() -> bool {
    if (replies.empty()) return true;
    bool ok = send_exact(fd, replies.data(), replies.size());
    replies.clear();
    return ok;
  };
  for (;;) {
    bool have_frame = false;
    if (rbuf.size() >= 4) {
      uint32_t len;
      std::memcpy(&len, rbuf.data(), 4);
      if (len > MAX_FRAME_LEN) {
        flush();
        break;
      }
      if (len > kBig && rbuf.size() < 4 + static_cast<size_t>(len)) {
        // large frame (device-memory write): fill the remainder straight
        // into the frame buffer with one recv_exact
        try {
          body.resize(len);
        } catch (const std::bad_alloc&) {
          flush();
          break;
        }
        size_t have = rbuf.size() - 4;
        std::memcpy(body.data(), rbuf.data() + 4, have);
        rbuf.clear();
        if (!recv_exact(fd, body.data() + have, len - have)) {
          flush();
          break;
        }
        have_frame = true;
      } else if (rbuf.size() >= 4 + static_cast<size_t>(len)) {
        body.assign(rbuf.begin() + 4, rbuf.begin() + 4 + len);
        rbuf.erase(rbuf.begin(), rbuf.begin() + 4 + len);
        have_frame = true;
      }
    }
    if (!have_frame) {
      if (!flush()) break;  // no complete frame left: flush the batch
      ssize_t r = ::recv(fd, chunk, sizeof chunk, 0);
      if (r <= 0) break;
      rbuf.insert(rbuf.end(), chunk, chunk + r);
      continue;
    }
    if (body.empty()) {
      flush();
      break;
    }
    std::vector<uint8_t> reply;
    try {
      reply = handle(body, &last_call_id);
    } catch (const std::exception& e) {
      // any throwing handler (bad_alloc included) answers with an
      // error instead of terminating the daemon (parity with the
      // Python daemon's guarded _serve_conn)
      std::fprintf(stderr, "request kind %u failed: %s\n", body[0],
                   e.what());
      reply = status_reply(E_INVALID);
    }
    if (reply.size() > kBig) {
      // big readback: scatter-gather send, zero extra copy
      if (!flush() || !send_frame(fd, reply)) break;
    } else {
      uint32_t rlen = static_cast<uint32_t>(reply.size());
      replies.insert(replies.end(), reinterpret_cast<uint8_t*>(&rlen),
                     reinterpret_cast<uint8_t*>(&rlen) + 4);
      replies.insert(replies.end(), reply.begin(), reply.end());
    }
    if (body[0] == MSG_SHUTDOWN) {
      flush();
      shutting_down.store(true);
      call_cv_.notify_all();
      {
        std::lock_guard<std::mutex> elk(eth_mu_);  // vs stack swap
        eth_->stop();
      }
      ::close(fd);
      ::exit(0);
    }
  }
  ::close(fd);
}

std::vector<uint8_t> RankDaemon::handle(const std::vector<uint8_t>& body,
                                        uint32_t* last_call_id) {
  const uint8_t kind = body[0];
  const uint8_t* p = body.data() + 1;
  const size_t len = body.size() - 1;  // payload bytes after the kind
  // minimum payload per message kind: a truncated/garbage frame must get
  // an INVALID reply, never read past the buffer (robustness parity with
  // the Python daemon's guarded handler)
  size_t need = 0;
  switch (kind) {
    case MSG_ALLOC: case MSG_READ_MEM: need = 16; break;
    case MSG_FREE: case MSG_WRITE_MEM: case MSG_SET_TIMEOUT:
    case MSG_SET_SEG: need = 8; break;
    case MSG_WAIT: need = 4; break;
    case MSG_CALL: need = 54; break;       // fixed descriptor layout
    //   (8B flags + u64 count + 3xu32 + 3xu64 addrs + u16 n_waitfor —
    //   matches protocol.py pack_call's struct calcsize)
    case MSG_CONFIG_COMM: need = 12; break;
    default: break;                        // stream msgs validate inline
  }
  if (len < need) return status_reply(E_INVALID);
  switch (kind) {
    case MSG_PING:
      return status_reply(E_OK);
    case MSG_ALLOC: {
      uint64_t nbytes = get_le<uint64_t>(p + 8);
      if (nbytes > MAX_ALLOC_BYTES) return status_reply(E_DMA_SIZE);
      mem_.alloc(get_le<uint64_t>(p), nbytes);
      return status_reply(E_OK);
    }
    case MSG_FREE:
      mem_.free_region(get_le<uint64_t>(p));
      return status_reply(E_OK);
    case MSG_WRITE_MEM: {
      uint64_t addr = get_le<uint64_t>(p);
      bool ok = mem_.write(addr, p + 8, body.size() - 9);
      return status_reply(ok ? E_OK : E_INVALID);
    }
    case MSG_READ_MEM: {
      uint64_t addr = get_le<uint64_t>(p);
      uint64_t nbytes = get_le<uint64_t>(p + 8);
      // validate BEFORE sizing the reply: a hostile nbytes would
      // otherwise bad_alloc (registered regions are <= MAX_ALLOC_BYTES)
      if (!mem_.valid(addr, nbytes)) return status_reply(E_INVALID);
      std::vector<uint8_t> reply{MSG_DATA};
      reply.resize(1 + nbytes);
      if (!mem_.read(addr, reply.data() + 1, nbytes))
        return status_reply(E_INVALID);
      return reply;
    }
    case MSG_CONFIG_COMM: {
      Communicator comm;
      comm.comm_id = get_le<uint32_t>(p);
      comm.local_rank = get_le<uint32_t>(p + 4);
      uint32_t n = get_le<uint32_t>(p + 8);
      size_t off = 12;
      // parse the ENTIRE table before applying any side effect: a frame
      // rejected as truncated must not leave partially-learned peers
      // (the Python daemon's unpack_comm raises before learn_peers too)
      for (uint32_t i = 0; i < n; ++i) {
        if (off + 8 > len) return status_reply(E_INVALID);
        RankInfo ri;
        ri.global_rank = get_le<uint32_t>(p + off);
        ri.cmd_port = get_le<uint16_t>(p + off + 4);
        uint16_t hlen = get_le<uint16_t>(p + off + 6);
        off += 8;
        if (off + hlen > len) return status_reply(E_INVALID);
        ri.host.assign(reinterpret_cast<const char*>(p + off), hlen);
        off += hlen;
        comm.ranks.push_back(ri);
      }
      // optional trailing tenant record (tenant_len u16 + utf-8): the
      // multi-tenant service grouping. Absent in frames from older
      // clients — and tolerated absent, so the extension is
      // wire-compatible in both directions (protocol.py pack_comm).
      if (off + 2 <= len) {
        uint16_t tlen = get_le<uint16_t>(p + off);
        off += 2;
        if (off + tlen > len) return status_reply(E_INVALID);
        comm.tenant.assign(reinterpret_cast<const char*>(p + off), tlen);
        off += tlen;
      }
      for (const auto& ri : comm.ranks) {
        if (ri.global_rank != rank_ && ri.cmd_port) {
          std::lock_guard<std::mutex> elk(eth_mu_);  // vs stack swap
          eth_->learn_peer(ri.global_rank, ri.host,
                           static_cast<uint16_t>(ri.cmd_port + world_));
        }
      }
      std::lock_guard<std::mutex> lk(comm_mu_);
      comms_[comm.comm_id] = comm;
      return status_reply(E_OK);
    }
    case MSG_SET_TIMEOUT: {
      double t;
      std::memcpy(&t, p, 8);
      // feeds wait_until deadlines later
      timeout_ = sane_budget(t, /*configured=*/true);
      return status_reply(E_OK);
    }
    case MSG_SET_SEG: {
      uint64_t s = get_le<uint64_t>(p);
      if (s > bufsize_) return status_reply(E_DMA_SIZE);
      max_seg_ = s;
      return status_reply(E_OK);
    }
    case MSG_CALL: {
      std::lock_guard<std::mutex> lk(call_mu_);
      uint32_t id = next_call_id_++;
      std::vector<uint8_t> desc(body.begin() + 1, body.end());
      // WAITFOR_PREV (0xFFFFFFFF) resolves to the previous call THIS
      // connection submitted — not id-1, which another connection's
      // interleaved MSG_CALL could claim as its own id
      if (desc.size() >= 54) {
        uint16_t nw = get_le<uint16_t>(desc.data() + 52);
        size_t off = 54;
        for (uint16_t i = 0; i < nw && off + 4 <= desc.size();
             ++i, off += 4) {
          if (get_le<uint32_t>(desc.data() + off) == 0xFFFFFFFFu) {
            uint32_t prev = last_call_id ? *last_call_id : id - 1;
            desc[off] = static_cast<uint8_t>(prev);
            desc[off + 1] = static_cast<uint8_t>(prev >> 8);
            desc[off + 2] = static_cast<uint8_t>(prev >> 16);
            desc[off + 3] = static_cast<uint8_t>(prev >> 24);
          }
        }
      }
      call_queue_.emplace_back(id, std::move(desc));
      call_cv_.notify_all();
      if (last_call_id) *last_call_id = id;
      std::vector<uint8_t> reply{MSG_CALL_ID};
      put_le<uint32_t>(reply, id);
      return reply;
    }
    case MSG_WAIT: {
      uint32_t id = get_le<uint32_t>(p);
      if (id == 0xFFFFFFFFu && last_call_id)  // WAIT_LAST sentinel
        id = *last_call_id;
      double budget = timeout_;
      if (body.size() >= 13) std::memcpy(&budget, p + 4, 8);
      std::unique_lock<std::mutex> lk(call_mu_);
      auto deadline = std::chrono::steady_clock::now() +
                      std::chrono::duration<double>(sane_budget(budget));
      wait_active_[id]++;
      bool pending = false;
      while (call_status_.find(id) == call_status_.end()) {
        if (id <= evicted_max_) {
          // evicted after retirement: FIFO means it DID retire; a
          // failure survives in failed_calls_ — unless it TOO aged out
          // of the bounded failure FIFO, in which case the outcome is
          // unknowable and 0 would be a fabricated success
          if (--wait_active_[id] == 0) wait_active_.erase(id);
          auto f = failed_calls_.find(id);
          if (f != failed_calls_.end()) return fail_reply(id, f->second);
          return status_reply(
              id <= failed_evicted_max_ ? (uint32_t)E_OUTCOME_UNKNOWN : 0u);
        }
        if (call_cv_.wait_until(lk, deadline) == std::cv_status::timeout) {
          pending = true;
          break;
        }
      }
      if (--wait_active_[id] == 0) wait_active_.erase(id);
      if (pending) return status_reply(STATUS_PENDING);
      uint32_t err = call_status_[id];
      call_status_.erase(id);
      return err ? fail_reply(id, err) : status_reply(err);
    }
    case MSG_GET_INFO: {
      // base geometry + config-state extension (readable effect of the
      // runtime config calls; layout matches the Python daemon)
      std::vector<uint8_t> reply{MSG_DATA};
      put_le<uint64_t>(reply, bufsize_);
      put_le<uint32_t>(reply, (uint32_t)nbufs_);
      put_le<uint32_t>(reply, world_);
      put_le<uint32_t>(reply, rank_);
      put_le<uint64_t>(reply, (uint64_t)max_seg_);
      put_le<uint32_t>(reply, (uint32_t)(timeout_ * 1000.0));
      reply.push_back((pkt_enabled_ ? 1 : 0) | (profiling_ ? 2 : 0));
      {
        std::lock_guard<std::mutex> elk(eth_mu_);  // vs stack swap
        reply.push_back(eth_->is_udp() ? 1 : 0);
      }
      put_le<uint32_t>(reply, profiled_calls_);
      // capability word (keep in sync with protocol.py CAP_*): this
      // daemon speaks the UDP selective-retransmission ACK lane
      // (CAP_RETX_ACK — python peers stop pinning their retx window to
      // 0) and, unless $ACCL_TPU_CSUM disables it, trailing-crc32c
      // payload integrity (CAP_CSUM | CAP_CSUM_C, bit-identical to
      // google-crc32c). CAP_RMA and CAP_SHM stay clear: the one-sided
      // RMA engine and the shm dataplane remain python-tier lanes.
      {
        std::lock_guard<std::mutex> elk(eth_mu_);  // vs stack swap
        uint32_t caps = CAP_RETX_ACK;
        if (eth_->csum_enabled()) caps |= CAP_CSUM | CAP_CSUM_C;
        put_le<uint32_t>(reply, caps);
      }
      return reply;
    }
    case MSG_STREAM_PUSH: {
      // the payload must be whole elements of the declared dtype — a
      // ragged tail would leave unconsumable bytes in the port
      if (body.size() < 2 ||
          (body.size() - 2) % dtype_size(body[1]) != 0)
        return status_reply(E_INVALID);
      // body: dtype u8 + raw elements — synthesize an envelope so the
      // executor's M_STREAM fetch sees the host-fed dtype
      Envelope env;
      env.dtype = body[1];
      env.nbytes = body.size() - 2;
      std::vector<uint8_t> payload(body.begin() + 2, body.end());
      {
        std::lock_guard<std::mutex> lk(stream_mu_);
        stream_in_.push_back({env, std::move(payload)});
        stream_cv_.notify_all();
      }
      return status_reply(E_OK);
    }
    case MSG_STREAM_POP: {
      if (body.size() < 9) return status_reply(E_INVALID);
      double budget;
      std::memcpy(&budget, p, 8);
      uint64_t count = body.size() >= 17 ? get_le<uint64_t>(p + 8) : 0;
      std::unique_lock<std::mutex> lk(stream_mu_);
      auto deadline = std::chrono::steady_clock::now() +
                      std::chrono::duration<double>(sane_budget(budget));
      if (count == 0) {
        // next entry whole
        while (stream_out_.empty()) {
          if (stream_cv_.wait_until(lk, deadline) == std::cv_status::timeout)
            return status_reply(STATUS_PENDING);
        }
        auto item = std::move(stream_out_.front());
        stream_out_.pop_front();
        std::vector<uint8_t> reply{MSG_DATA, item.first};
        reply.insert(reply.end(), item.second.begin() + stream_out_off_,
                     item.second.end());
        stream_out_off_ = 0;
        return reply;
      }
      // exactly `count` elements across entries (continuous semantics);
      // entries are produced in the call's uncompressed dtype, so the
      // head entry's dtype types the reply
      auto dtfn = [](const std::pair<uint8_t, std::vector<uint8_t>>& e) {
        return e.first;
      };
      while (stream_out_.empty() ||
             stream_avail(stream_out_, stream_out_off_, dtfn) < count) {
        if (stream_cv_.wait_until(lk, deadline) == std::cv_status::timeout)
          return status_reply(STATUS_PENDING);
      }
      uint8_t dt = stream_out_.front().first;
      std::vector<uint8_t> reply{MSG_DATA, dt};
      auto data = stream_take(stream_out_, stream_out_off_, count, dt, dtfn);
      reply.insert(reply.end(), data.begin(), data.end());
      return reply;
    }
    case MSG_RESET: {
      soft_reset();
      return status_reply(E_OK);
    }
    case MSG_DUMP_RX: {
      // pool geometry + the native counter families as text lines
      // (chaos/observability harnesses parse `name=value` pairs here,
      // like the python daemons' counter dumps)
      std::string s = pool_.describe();
      char line[512];
      snprintf(line, sizeof line,
               "\nretx: tracked=%llu retransmits=%llu rto_fires=%llu "
               "fast_retransmits=%llu acked=%llu dedup_dropped=%llu "
               "horizon_dropped=%llu gave_up=%llu window_stalls=%llu "
               "acks_sent=%llu",
               (unsigned long long)retx_tracked_.load(),
               (unsigned long long)retx_retransmits_.load(),
               (unsigned long long)retx_rto_fires_.load(),
               (unsigned long long)retx_fast_retransmits_.load(),
               (unsigned long long)retx_acked_.load(),
               (unsigned long long)retx_dedup_dropped_.load(),
               (unsigned long long)retx_horizon_dropped_.load(),
               (unsigned long long)retx_gave_up_.load(),
               (unsigned long long)retx_window_stalls_.load(),
               (unsigned long long)retx_acks_sent_.load());
      s += line;
      snprintf(line, sizeof line, "\nintegrity: failed=%llu",
               (unsigned long long)integrity_failed_.load());
      s += line;
      snprintf(line, sizeof line,
               "\ncodec: bs_encoded=%llu bs_decoded=%llu simd_level=%d",
               (unsigned long long)bs_encoded_segs_.load(),
               (unsigned long long)bs_decoded_segs_.load(), bsc_level());
      s += line;
      std::vector<uint8_t> reply{MSG_DATA};
      reply.insert(reply.end(), s.begin(), s.end());
      return reply;
    }
    case MSG_SHUTDOWN:
      return status_reply(E_OK);
    default:
      return status_reply(E_INVALID);
  }
}

// ---------------------------------------------------------------------------
int main(int argc, char** argv) {
  uint32_t rank = 0, world = 1;
  uint16_t port_base = 45000;
  size_t nbufs = 16, bufsize = 1 << 20;
  bool udp = false;
  for (int i = 1; i + 1 < argc; i += 2) {
    std::string k = argv[i];
    const char* v = argv[i + 1];
    if (k == "--rank") rank = atoi(v);
    else if (k == "--world") world = atoi(v);
    else if (k == "--port-base") port_base = atoi(v);
    else if (k == "--nbufs") nbufs = atoi(v);
    else if (k == "--bufsize") bufsize = atoll(v);
    else if (k == "--stack") udp = (std::string(v) == "udp");
  }
  bsc_init();  // resolve the codec SIMD level once, before any traffic
  RankDaemon daemon(rank, world, port_base, nbufs, bufsize, udp);
  return daemon.serve(static_cast<uint16_t>(port_base + rank));
}
