/* bs_codec.h: shared block-scaled quantization codec (scalar + SIMD).
 *
 * Single source of truth for the fp8/int8 encode/decode/combine kernels
 * used by BOTH native components: _accl_combine (the Python emulator's
 * compiled combine library, combine_kernels.c) and cclo_emud (the C++
 * rank daemon's C_BLOCK_SCALED wire lanes).  Header-only, all-static,
 * compiles as C11 and C++17.
 *
 * Contract: every path — scalar, SSE2, AVX2 — is BIT-IDENTICAL to the
 * numpy reference in accl_tpu/quant.py (and therefore to ml_dtypes'
 * float8 casts), pinned by tests/test_combine_native.py over the full
 * 256-code product and a dense f32 corpus including +-0/NaN/inf.  The
 * vector paths achieve this by construction, not by luck:
 *
 *   - fp8 ENCODE rides an integer fast path that is exact
 *     round-to-nearest-even on the f32 bit pattern:
 *         rounded = (A + (1<<(shift-1)) - 1 + ((A>>shift)&1)) >> shift
 *         code    = rounded - ((127-bias) << man_bits)
 *     valid whenever the pre-round target exponent is >= 1.  The hard
 *     lanes — subnormal/underflow targets (A < min_norm), inf/NaN
 *     inputs (A >= 0x7F800000) and overflow past the largest finite
 *     code — are detected with integer compares and patched through
 *     the scalar bsc_float_to_f8 (the mulps product equals the scalar
 *     multiply bit-for-bit, so the patch input is identical).
 *   - int8 ENCODE clamps to [-127, 127] in float and converts with
 *     cvtps2dq under the default MXCSR round-to-nearest-even — provably
 *     equal to the scalar rintf-then-clip for every input (ties like
 *     127.5 round to 128 then clip; clamp-first yields 127 as well);
 *     non-finite lanes are masked to 0 afterwards.
 *   - DECODE goes through a 256-entry f32 LUT built once from the
 *     scalar converters (exact by construction), then one mulps by the
 *     block scale — the same single rounding the scalar performs.
 *   - ABSMAX tracks NaN with a separate accumulated cmpunord mask
 *     (maxps quietly drops NaNs depending on operand order); any NaN,
 *     like the scalar NaN-propagating max, forces the identity scale.
 *   - MAX/MIN combine is a pure blend on cmpgt|cmpunord — selection,
 *     never arithmetic, so numpy's strict-compare tie rule survives.
 *
 * Dispatch: runtime-selected level 0=scalar / 1=SSE2 / 2=AVX2 via
 * __builtin_cpu_supports, overridable with ACCL_TPU_CODEC_SIMD (clamped
 * to what the host supports) or programmatically via bsc_set_level —
 * the hook the bit-identity tests use to prove every path on one host.
 * Non-x86 builds compile the scalar path only.
 */
#ifndef ACCL_BS_CODEC_H
#define ACCL_BS_CODEC_H

#include <float.h>
#include <math.h>
#include <stddef.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>

#if defined(__x86_64__) && (defined(__GNUC__) || defined(__clang__))
#define BSC_X86 1
#include <immintrin.h>
#else
#define BSC_X86 0
#endif

/* quantized-kind codes (independent of the wire dtype codes) */
#define BSC_QK_I8 0
#define BSC_QK_E4M3 1
#define BSC_QK_E5M2 2

/* func codes (accl_tpu.constants.ReduceFunc) */
#define BSC_F_SUM 0
#define BSC_F_MAX 1
#define BSC_F_MIN 2
#define BSC_F_PROD 3

/* ---- scalar fp8 conversion (ml_dtypes parity; the former
 * combine_kernels.c implementation, verbatim).  e4m3fn: 4 exp / 3 man,
 * bias 7, NO inf — all-ones exponent codes are ordinary values except
 * mantissa 111 (0x7F/0xFF = NaN).  e5m2: 5 exp / 2 man, bias 15,
 * IEEE-shaped (overflow -> inf 0x7C, NaN -> 0x7E).  Round-to-nearest-
 * even everywhere including the subnormal range. ---- */

static inline float bsc_f8_to_float(uint8_t h, int man_bits, int bias,
                                    int has_inf) {
    uint32_t sign = (uint32_t)(h & 0x80u) << 24;
    int exp_bits = 7 - man_bits;
    uint32_t man_mask = (1u << man_bits) - 1u;
    uint32_t exp = ((uint32_t)h >> man_bits) & ((1u << exp_bits) - 1u);
    uint32_t man = h & man_mask;
    uint32_t emax = (1u << exp_bits) - 1u;
    uint32_t f;
    if (exp == emax && (has_inf || man == man_mask)) {
        f = sign | (man ? 0x7FC00000u : (has_inf ? 0x7F800000u
                                                 : 0x7FC00000u));
    } else if (exp == 0) {
        if (man == 0) {
            f = sign;
        } else { /* subnormal: renormalize into f32 */
            uint32_t e = 127u - (uint32_t)bias + 1u;
            while (!(man & (1u << man_bits))) { man <<= 1; e--; }
            man &= man_mask;
            f = sign | (e << 23) | (man << (23 - man_bits));
        }
    } else {
        f = sign | ((exp - (uint32_t)bias + 127u) << 23)
            | (man << (23 - man_bits));
    }
    float out;
    memcpy(&out, &f, 4);
    return out;
}

static inline uint8_t bsc_float_to_f8(float v, int man_bits, int bias,
                                      int has_inf) {
    uint32_t x;
    memcpy(&x, &v, 4);
    uint8_t sign = (uint8_t)((x >> 24) & 0x80u);
    uint32_t fexp = (x >> 23) & 0xFFu;
    uint32_t man = x & 0x7FFFFFu;
    int exp_bits = 7 - man_bits;
    uint32_t emax = (1u << exp_bits) - 1u;
    /* largest finite code magnitude: e5m2 0x7B, e4m3fn 0x7E */
    uint8_t max_code = (uint8_t)(has_inf ? ((emax << man_bits) - 1u)
                                         : ((emax << man_bits)
                                            | ((1u << man_bits) - 2u)));
    uint8_t inf_code = (uint8_t)(emax << man_bits);         /* e5m2 only */
    uint8_t nan_code = (uint8_t)(has_inf ? (inf_code | 0x02u)
                                         : ((emax << man_bits)
                                            | ((1u << man_bits) - 1u)));
    if (fexp == 0xFFu) {
        if (man)                            /* NaN: canonical quiet code */
            return sign | nan_code;
        return sign | (has_inf ? inf_code : nan_code);  /* inf */
    }
    int exp = (int)fexp - 127 + bias;
    int shift = 23 - man_bits;
    uint32_t out;
    if (exp <= 0) { /* subnormal target (or underflow to zero) */
        if (exp < -man_bits)
            return sign;
        man |= 0x800000u;                   /* implicit bit */
        uint32_t s = (uint32_t)(shift + 1 - exp);
        uint32_t hman = man >> s;
        uint32_t rem = man & ((1u << s) - 1u);
        uint32_t halfway = 1u << (s - 1);
        if (rem > halfway || (rem == halfway && (hman & 1u)))
            hman++;
        out = hman;                         /* may carry into exp 1: fine */
    } else {
        uint32_t rem = man & ((1u << shift) - 1u);
        uint32_t hman = man >> shift;
        out = ((uint32_t)exp << man_bits) | hman;
        uint32_t halfway = 1u << (shift - 1);
        if (rem > halfway || (rem == halfway && (hman & 1u)))
            out++;                          /* carry may bump the exp */
    }
    if (out > max_code)                     /* overflow past max finite */
        return sign | (has_inf ? inf_code : nan_code);
    return sign | (uint8_t)out;
}

static inline float bsc_qmax_of(int qk) {
    return qk == BSC_QK_I8 ? 127.0f
                           : (qk == BSC_QK_E4M3 ? 448.0f : 57344.0f);
}

static inline float bsc_q_decode(int qk, uint8_t raw) {
    switch (qk) {
    case BSC_QK_I8: return (float)(int8_t)raw;
    case BSC_QK_E4M3: return bsc_f8_to_float(raw, 3, 7, 0);
    default: return bsc_f8_to_float(raw, 2, 15, 1);
    }
}

static inline uint8_t bsc_q_encode(int qk, float v) {
    if (qk == BSC_QK_I8) {
        if (!isfinite(v))
            return 0;               /* NaN/inf quantize to 0 (reference) */
        float r = rintf(v);         /* round half to even, like np.rint */
        if (r > 127.0f) r = 127.0f;
        if (r < -127.0f) r = -127.0f;
        return (uint8_t)(int8_t)r;
    }
    return qk == BSC_QK_E4M3 ? bsc_float_to_f8(v, 3, 7, 0)
                             : bsc_float_to_f8(v, 2, 15, 1);
}

/* ---- decode LUTs (one f32 per code, built from the scalar converters
 * so they are exact by definition).  bsc_init() populates them before
 * any thread can race; the lazy fallback writes are idempotent (every
 * writer stores identical bytes) with the ready flag set last. ---- */

static float bsc_lut_[3][256];
static volatile int bsc_lut_ready_ = 0;

static inline void bsc_build_luts(void) {
    for (int c = 0; c < 256; c++) {
        bsc_lut_[BSC_QK_I8][c] = (float)(int8_t)(uint8_t)c;
        bsc_lut_[BSC_QK_E4M3][c] = bsc_f8_to_float((uint8_t)c, 3, 7, 0);
        bsc_lut_[BSC_QK_E5M2][c] = bsc_f8_to_float((uint8_t)c, 2, 15, 1);
    }
    bsc_lut_ready_ = 1;
}

static inline const float *bsc_lut(int qk) {
    if (!bsc_lut_ready_) bsc_build_luts();
    return bsc_lut_[qk];
}

/* ---- runtime dispatch level ------------------------------------------- */

static int bsc_level_ = -1;      /* resolved level: 0 scalar, 1 SSE2, 2 AVX2 */
static int bsc_max_level_ = 0;   /* what this host supports */

static inline int bsc_detect_max(void) {
#if BSC_X86
    return __builtin_cpu_supports("avx2") ? 2 : 1;
#else
    return 0;
#endif
}

static inline void bsc_init(void) {
    bsc_build_luts();
    bsc_max_level_ = bsc_detect_max();
    int lvl = bsc_max_level_;
    const char *env = getenv("ACCL_TPU_CODEC_SIMD");
    if (env && *env) {
        int want = atoi(env);
        if (want < 0) want = 0;
        if (want < lvl) lvl = want;
    }
    bsc_level_ = lvl;
}

static inline int bsc_level(void) {
    if (bsc_level_ < 0) bsc_init();
    return bsc_level_;
}

/* clamp to host support; returns the level actually in effect */
static inline int bsc_set_level(int lvl) {
    if (bsc_level_ < 0) bsc_init();
    if (lvl < 0) lvl = 0;
    if (lvl > bsc_max_level_) lvl = bsc_max_level_;
    bsc_level_ = lvl;
    return bsc_level_;
}

/* ---- SIMD kernels ------------------------------------------------------ */
#if BSC_X86

/* fp8 encode, 16 floats/iter: integer RNE fast path + scalar patch of
 * the hard lanes (subnormal target / inf / NaN / overflow). */
static inline void bsc_enc_f8_sse2(int man_bits, int bias, int has_inf,
                                   const float *x, float inv, uint8_t *q,
                                   ptrdiff_t bn) {
    const int shift = 23 - man_bits;
    const int emax = (1 << (7 - man_bits)) - 1;
    const int max_code = has_inf ? ((emax << man_bits) - 1)
                                 : ((emax << man_bits)
                                    | ((1 << man_bits) - 2));
    const __m128 vinv = _mm_set1_ps(inv);
    const __m128i vabs = _mm_set1_epi32(0x7FFFFFFF);
    const __m128i vone = _mm_set1_epi32(1);
    const __m128i vhalfm1 = _mm_set1_epi32((1 << (shift - 1)) - 1);
    const __m128i vrebias = _mm_set1_epi32((127 - bias) << man_bits);
    const __m128i vminnorm = _mm_set1_epi32((127 - bias + 1) << 23);
    const __m128i vinfm1 = _mm_set1_epi32(0x7F7FFFFF);
    const __m128i vmaxcode = _mm_set1_epi32(max_code);
    const __m128i vsignb = _mm_set1_epi32(0x80);
    const __m128i vbyte = _mm_set1_epi32(0xFF);
    ptrdiff_t i = 0;
    for (; i + 16 <= bn; i += 16) {
        __m128i c[4];
        uint32_t hard = 0;
        for (int k = 0; k < 4; k++) {
            __m128 p = _mm_mul_ps(_mm_loadu_ps(x + i + 4 * k), vinv);
            __m128i bits = _mm_castps_si128(p);
            __m128i A = _mm_and_si128(bits, vabs);
            __m128i lsb = _mm_and_si128(_mm_srli_epi32(A, shift), vone);
            __m128i rounded = _mm_srli_epi32(
                _mm_add_epi32(_mm_add_epi32(A, vhalfm1), lsb), shift);
            __m128i code = _mm_sub_epi32(rounded, vrebias);
            __m128i sign = _mm_and_si128(_mm_srli_epi32(bits, 24), vsignb);
            __m128i hm = _mm_or_si128(
                _mm_or_si128(_mm_cmplt_epi32(A, vminnorm),
                             _mm_cmpgt_epi32(A, vinfm1)),
                _mm_cmpgt_epi32(code, vmaxcode));
            hard |= (uint32_t)_mm_movemask_ps(_mm_castsi128_ps(hm))
                    << (4 * k);
            c[k] = _mm_and_si128(_mm_or_si128(code, sign), vbyte);
        }
        __m128i w0 = _mm_packs_epi32(c[0], c[1]);
        __m128i w1 = _mm_packs_epi32(c[2], c[3]);
        _mm_storeu_si128((__m128i *)(q + i), _mm_packus_epi16(w0, w1));
        while (hard) {
            int j = __builtin_ctz(hard);
            hard &= hard - 1;
            q[i + j] = bsc_float_to_f8(x[i + j] * inv, man_bits, bias,
                                       has_inf);
        }
    }
    for (; i < bn; i++)
        q[i] = bsc_float_to_f8(x[i] * inv, man_bits, bias, has_inf);
}

/* int8 encode, 16 floats/iter: clamp to +-127 in float, cvtps2dq under
 * the default round-to-nearest-even MXCSR, non-finite masked to 0. */
static inline void bsc_enc_i8_sse2(const float *x, float inv, uint8_t *q,
                                   ptrdiff_t bn) {
    const __m128 vinv = _mm_set1_ps(inv);
    const __m128 vlo = _mm_set1_ps(-127.0f);
    const __m128 vhi = _mm_set1_ps(127.0f);
    const __m128i vabs = _mm_set1_epi32(0x7FFFFFFF);
    const __m128i vinf = _mm_set1_epi32(0x7F800000);
    ptrdiff_t i = 0;
    for (; i + 16 <= bn; i += 16) {
        __m128i c[4];
        for (int k = 0; k < 4; k++) {
            __m128 p = _mm_mul_ps(_mm_loadu_ps(x + i + 4 * k), vinv);
            __m128i A = _mm_and_si128(_mm_castps_si128(p), vabs);
            __m128i finite = _mm_cmplt_epi32(A, vinf);
            __m128 cl = _mm_min_ps(_mm_max_ps(p, vlo), vhi);
            c[k] = _mm_and_si128(_mm_cvtps_epi32(cl), finite);
        }
        __m128i w0 = _mm_packs_epi32(c[0], c[1]);
        __m128i w1 = _mm_packs_epi32(c[2], c[3]);
        _mm_storeu_si128((__m128i *)(q + i), _mm_packs_epi16(w0, w1));
    }
    for (; i < bn; i++)
        q[i] = bsc_q_encode(BSC_QK_I8, x[i] * inv);
}

/* LUT decode + scale multiply, 4/iter */
static inline void bsc_dec_sse2(const float *lut, const uint8_t *q,
                                float s, float *out, ptrdiff_t bn) {
    const __m128 vs = _mm_set1_ps(s);
    ptrdiff_t i = 0;
    for (; i + 4 <= bn; i += 4) {
        __m128 v = _mm_setr_ps(lut[q[i]], lut[q[i + 1]], lut[q[i + 2]],
                               lut[q[i + 3]]);
        _mm_storeu_ps(out + i, _mm_mul_ps(v, vs));
    }
    for (; i < bn; i++)
        out[i] = lut[q[i]] * s;
}

/* fused dequant+combine.  MAX/MIN are pure blends on cmpgt|cmpunord so
 * numpy's strict-compare/second-wins-ties/NaN-propagates rule holds
 * bit-for-bit (FMAX_NP semantics). */
static inline void bsc_comb_sse2(int func, const float *lut,
                                 const uint8_t *q, float s,
                                 const float *other, float *out,
                                 ptrdiff_t bn) {
    const __m128 vs = _mm_set1_ps(s);
    ptrdiff_t i = 0;
    for (; i + 4 <= bn; i += 4) {
        __m128 v = _mm_mul_ps(
            _mm_setr_ps(lut[q[i]], lut[q[i + 1]], lut[q[i + 2]],
                        lut[q[i + 3]]),
            vs);
        __m128 o = _mm_loadu_ps(other + i);
        __m128 r;
        switch (func) {
        case BSC_F_SUM: r = _mm_add_ps(o, v); break;
        case BSC_F_PROD: r = _mm_mul_ps(o, v); break;
        case BSC_F_MAX: {
            __m128 m = _mm_or_ps(_mm_cmpgt_ps(o, v),
                                 _mm_cmpunord_ps(o, o));
            r = _mm_or_ps(_mm_and_ps(m, o), _mm_andnot_ps(m, v));
            break;
        }
        default: { /* BSC_F_MIN */
            __m128 m = _mm_or_ps(_mm_cmplt_ps(o, v),
                                 _mm_cmpunord_ps(o, o));
            r = _mm_or_ps(_mm_and_ps(m, o), _mm_andnot_ps(m, v));
            break;
        }
        }
        _mm_storeu_ps(out + i, r);
    }
    for (; i < bn; i++) {
        float v = lut[q[i]] * s;
        float o = other[i];
        switch (func) {
        case BSC_F_SUM: out[i] = o + v; break;
        case BSC_F_PROD: out[i] = o * v; break;
        case BSC_F_MAX: out[i] = (o > v || isnan(o)) ? o : v; break;
        default: out[i] = (o < v || isnan(o)) ? o : v; break;
        }
    }
}

/* blockwise absmax with the NaN flag tracked separately — maxps drops
 * NaNs (it returns the second operand on unordered compares), so the
 * scalar's NaN-propagating max is reproduced via an accumulated
 * cmpunord mask instead. */
static inline float bsc_absmax_sse2(const float *x, ptrdiff_t bn) {
    const __m128 vabs = _mm_castsi128_ps(_mm_set1_epi32(0x7FFFFFFF));
    __m128 vm = _mm_setzero_ps();
    __m128 vnan = _mm_setzero_ps();
    ptrdiff_t i = 0;
    for (; i + 4 <= bn; i += 4) {
        __m128 v = _mm_loadu_ps(x + i);
        vnan = _mm_or_ps(vnan, _mm_cmpunord_ps(v, v));
        vm = _mm_max_ps(vm, _mm_and_ps(v, vabs));
    }
    if (_mm_movemask_ps(vnan))
        return NAN;
    float lanes[4];
    _mm_storeu_ps(lanes, vm);
    float m = lanes[0];
    for (int k = 1; k < 4; k++)
        if (lanes[k] > m) m = lanes[k];
    for (; i < bn; i++) {
        float av = fabsf(x[i]);
        if (isnan(av) || av > m) m = av;
    }
    return m;
}

/* ---- AVX2 twins (compiled with a per-function target so the baseline
 * build stays SSE2-portable; entered only when cpuid says avx2) ---- */

__attribute__((target("avx2"))) static inline void bsc_enc_f8_avx2(
    int man_bits, int bias, int has_inf, const float *x, float inv,
    uint8_t *q, ptrdiff_t bn) {
    const int shift = 23 - man_bits;
    const int emax = (1 << (7 - man_bits)) - 1;
    const int max_code = has_inf ? ((emax << man_bits) - 1)
                                 : ((emax << man_bits)
                                    | ((1 << man_bits) - 2));
    const __m256 vinv = _mm256_set1_ps(inv);
    const __m256i vabs = _mm256_set1_epi32(0x7FFFFFFF);
    const __m256i vone = _mm256_set1_epi32(1);
    const __m256i vhalfm1 = _mm256_set1_epi32((1 << (shift - 1)) - 1);
    const __m256i vrebias = _mm256_set1_epi32((127 - bias) << man_bits);
    const __m256i vminnorm = _mm256_set1_epi32((127 - bias + 1) << 23);
    const __m256i vinf = _mm256_set1_epi32(0x7F800000);
    const __m256i vmaxcode = _mm256_set1_epi32(max_code);
    const __m256i vsignb = _mm256_set1_epi32(0x80);
    const __m256i vbyte = _mm256_set1_epi32(0xFF);
    /* packs/packus interleave the two 128-bit lanes; this permute
     * restores sequential byte order (dwords 0,4,1,5,2,6,3,7) */
    const __m256i vperm = _mm256_setr_epi32(0, 4, 1, 5, 2, 6, 3, 7);
    ptrdiff_t i = 0;
    for (; i + 32 <= bn; i += 32) {
        __m256i c[4];
        uint32_t hard = 0;
        for (int k = 0; k < 4; k++) {
            __m256 p = _mm256_mul_ps(_mm256_loadu_ps(x + i + 8 * k), vinv);
            __m256i bits = _mm256_castps_si256(p);
            __m256i A = _mm256_and_si256(bits, vabs);
            __m256i lsb = _mm256_and_si256(_mm256_srli_epi32(A, shift),
                                           vone);
            __m256i rounded = _mm256_srli_epi32(
                _mm256_add_epi32(_mm256_add_epi32(A, vhalfm1), lsb),
                shift);
            __m256i code = _mm256_sub_epi32(rounded, vrebias);
            __m256i sign = _mm256_and_si256(_mm256_srli_epi32(bits, 24),
                                            vsignb);
            /* A >= inf == !(A < inf): cmpgt(vinf, A) inverted via the
             * or-of-three shape below needs A > inf-1; keep the SSE2
             * formulation with a cmpgt against 0x7F7FFFFF */
            __m256i hm = _mm256_or_si256(
                _mm256_or_si256(
                    _mm256_cmpgt_epi32(vminnorm, A),
                    _mm256_cmpgt_epi32(A,
                                       _mm256_sub_epi32(vinf, vone))),
                _mm256_cmpgt_epi32(code, vmaxcode));
            hard |= (uint32_t)_mm256_movemask_ps(_mm256_castsi256_ps(hm))
                    << (8 * k);
            c[k] = _mm256_and_si256(_mm256_or_si256(code, sign), vbyte);
        }
        __m256i w0 = _mm256_packs_epi32(c[0], c[1]);
        __m256i w1 = _mm256_packs_epi32(c[2], c[3]);
        __m256i bytes = _mm256_permutevar8x32_epi32(
            _mm256_packus_epi16(w0, w1), vperm);
        _mm256_storeu_si256((__m256i *)(q + i), bytes);
        while (hard) {
            int j = __builtin_ctz(hard);
            hard &= hard - 1;
            q[i + j] = bsc_float_to_f8(x[i + j] * inv, man_bits, bias,
                                       has_inf);
        }
    }
    if (i < bn)
        bsc_enc_f8_sse2(man_bits, bias, has_inf, x + i, inv, q + i,
                        bn - i);
}

__attribute__((target("avx2"))) static inline void bsc_enc_i8_avx2(
    const float *x, float inv, uint8_t *q, ptrdiff_t bn) {
    const __m256 vinv = _mm256_set1_ps(inv);
    const __m256 vlo = _mm256_set1_ps(-127.0f);
    const __m256 vhi = _mm256_set1_ps(127.0f);
    const __m256i vabs = _mm256_set1_epi32(0x7FFFFFFF);
    const __m256i vinf = _mm256_set1_epi32(0x7F800000);
    const __m256i vperm = _mm256_setr_epi32(0, 4, 1, 5, 2, 6, 3, 7);
    ptrdiff_t i = 0;
    for (; i + 32 <= bn; i += 32) {
        __m256i c[4];
        for (int k = 0; k < 4; k++) {
            __m256 p = _mm256_mul_ps(_mm256_loadu_ps(x + i + 8 * k), vinv);
            __m256i A = _mm256_and_si256(_mm256_castps_si256(p), vabs);
            __m256i finite = _mm256_cmpgt_epi32(vinf, A);
            __m256 cl = _mm256_min_ps(_mm256_max_ps(p, vlo), vhi);
            c[k] = _mm256_and_si256(_mm256_cvtps_epi32(cl), finite);
        }
        __m256i w0 = _mm256_packs_epi32(c[0], c[1]);
        __m256i w1 = _mm256_packs_epi32(c[2], c[3]);
        __m256i bytes = _mm256_permutevar8x32_epi32(
            _mm256_packs_epi16(w0, w1), vperm);
        _mm256_storeu_si256((__m256i *)(q + i), bytes);
    }
    if (i < bn)
        bsc_enc_i8_sse2(x + i, inv, q + i, bn - i);
}

__attribute__((target("avx2"))) static inline void bsc_dec_avx2(
    const float *lut, const uint8_t *q, float s, float *out,
    ptrdiff_t bn) {
    const __m256 vs = _mm256_set1_ps(s);
    ptrdiff_t i = 0;
    for (; i + 8 <= bn; i += 8) {
        __m256i idx = _mm256_cvtepu8_epi32(
            _mm_loadl_epi64((const __m128i *)(q + i)));
        __m256 v = _mm256_i32gather_ps(lut, idx, 4);
        _mm256_storeu_ps(out + i, _mm256_mul_ps(v, vs));
    }
    for (; i < bn; i++)
        out[i] = lut[q[i]] * s;
}

__attribute__((target("avx2"))) static inline void bsc_comb_avx2(
    int func, const float *lut, const uint8_t *q, float s,
    const float *other, float *out, ptrdiff_t bn) {
    const __m256 vs = _mm256_set1_ps(s);
    ptrdiff_t i = 0;
    for (; i + 8 <= bn; i += 8) {
        __m256i idx = _mm256_cvtepu8_epi32(
            _mm_loadl_epi64((const __m128i *)(q + i)));
        __m256 v = _mm256_mul_ps(_mm256_i32gather_ps(lut, idx, 4), vs);
        __m256 o = _mm256_loadu_ps(other + i);
        __m256 r;
        switch (func) {
        case BSC_F_SUM: r = _mm256_add_ps(o, v); break;
        case BSC_F_PROD: r = _mm256_mul_ps(o, v); break;
        case BSC_F_MAX: {
            __m256 m = _mm256_or_ps(_mm256_cmp_ps(o, v, _CMP_GT_OQ),
                                    _mm256_cmp_ps(o, o, _CMP_UNORD_Q));
            r = _mm256_or_ps(_mm256_and_ps(m, o),
                             _mm256_andnot_ps(m, v));
            break;
        }
        default: {
            __m256 m = _mm256_or_ps(_mm256_cmp_ps(o, v, _CMP_LT_OQ),
                                    _mm256_cmp_ps(o, o, _CMP_UNORD_Q));
            r = _mm256_or_ps(_mm256_and_ps(m, o),
                             _mm256_andnot_ps(m, v));
            break;
        }
        }
        _mm256_storeu_ps(out + i, r);
    }
    if (i < bn)
        bsc_comb_sse2(func, lut, q + i, s, other + i, out + i, bn - i);
}

__attribute__((target("avx2"))) static inline float bsc_absmax_avx2(
    const float *x, ptrdiff_t bn) {
    const __m256 vabs = _mm256_castsi256_ps(
        _mm256_set1_epi32(0x7FFFFFFF));
    __m256 vm = _mm256_setzero_ps();
    __m256 vnan = _mm256_setzero_ps();
    ptrdiff_t i = 0;
    for (; i + 8 <= bn; i += 8) {
        __m256 v = _mm256_loadu_ps(x + i);
        vnan = _mm256_or_ps(vnan, _mm256_cmp_ps(v, v, _CMP_UNORD_Q));
        vm = _mm256_max_ps(vm, _mm256_and_ps(v, vabs));
    }
    if (_mm256_movemask_ps(vnan))
        return NAN;
    float lanes[8];
    _mm256_storeu_ps(lanes, vm);
    float m = lanes[0];
    for (int k = 1; k < 8; k++)
        if (lanes[k] > m) m = lanes[k];
    for (; i < bn; i++) {
        float av = fabsf(x[i]);
        if (isnan(av) || av > m) m = av;
    }
    return m;
}

#endif /* BSC_X86 */

/* ---- scalar reference loops (the portable fallback, and the baseline
 * the SIMD paths are tested bit-identical against) ---- */

static inline float bsc_absmax_scalar(const float *x, ptrdiff_t bn) {
    float m = 0.0f;
    for (ptrdiff_t i = 0; i < bn; i++) {
        float av = fabsf(x[i]);
        if (isnan(av) || av > m)    /* NaN-propagating max (np.max) */
            m = av;
    }
    return m;
}

static inline void bsc_enc_scalar(int qk, const float *x, float inv,
                                  uint8_t *q, ptrdiff_t bn) {
    for (ptrdiff_t i = 0; i < bn; i++)
        q[i] = bsc_q_encode(qk, x[i] * inv);
}

static inline void bsc_dec_scalar(int qk, const uint8_t *q, float s,
                                  float *out, ptrdiff_t bn) {
    for (ptrdiff_t i = 0; i < bn; i++)
        out[i] = bsc_q_decode(qk, q[i]) * s;
}

static inline int bsc_comb_scalar(int func, int qk, const uint8_t *q,
                                  float s, const float *other, float *out,
                                  ptrdiff_t bn) {
    for (ptrdiff_t i = 0; i < bn; i++) {
        float v = bsc_q_decode(qk, q[i]) * s;
        float o = other[i];
        switch (func) {
        case BSC_F_SUM: out[i] = o + v; break;
        case BSC_F_PROD: out[i] = o * v; break;
        case BSC_F_MAX: out[i] = (o > v || isnan(o)) ? o : v; break;
        case BSC_F_MIN: out[i] = (o < v || isnan(o)) ? o : v; break;
        default: return -1;
        }
    }
    return 0;
}

/* ---- public blockwise entry points ------------------------------------ */

static inline void bsc_quantize(int qk, ptrdiff_t block, const float *x,
                                float *scales, uint8_t *q, ptrdiff_t n) {
    int lvl = bsc_level();
    float qmax = bsc_qmax_of(qk);
    ptrdiff_t nb = (n + block - 1) / block;
    for (ptrdiff_t b = 0; b < nb; b++) {
        ptrdiff_t lo = b * block;
        ptrdiff_t hi = lo + block < n ? lo + block : n;
        ptrdiff_t bn = hi - lo;
        float m;
#if BSC_X86
        if (lvl == 2 && bn >= 8)
            m = bsc_absmax_avx2(x + lo, bn);
        else if (lvl >= 1 && bn >= 4)
            m = bsc_absmax_sse2(x + lo, bn);
        else
#endif
            m = bsc_absmax_scalar(x + lo, bn);
        float s = m / qmax;
        if (!(s >= FLT_MIN && s < INFINITY))
            s = 1.0f;     /* zero/subnormal/NaN/inf absmax: identity scale */
        scales[b] = s;
        float inv = 1.0f / s;
#if BSC_X86
        if (lvl >= 1 && bn >= 16) {
            if (qk == BSC_QK_I8) {
                if (lvl == 2)
                    bsc_enc_i8_avx2(x + lo, inv, q + lo, bn);
                else
                    bsc_enc_i8_sse2(x + lo, inv, q + lo, bn);
            } else {
                int mb = qk == BSC_QK_E4M3 ? 3 : 2;
                int bias = qk == BSC_QK_E4M3 ? 7 : 15;
                int hi8 = qk == BSC_QK_E5M2;
                if (lvl == 2)
                    bsc_enc_f8_avx2(mb, bias, hi8, x + lo, inv, q + lo, bn);
                else
                    bsc_enc_f8_sse2(mb, bias, hi8, x + lo, inv, q + lo, bn);
            }
            continue;
        }
#endif
        bsc_enc_scalar(qk, x + lo, inv, q + lo, bn);
    }
}

static inline void bsc_dequant(int qk, ptrdiff_t block, const float *scales,
                               const uint8_t *q, float *out, ptrdiff_t n) {
    int lvl = bsc_level();
    const float *lut = bsc_lut(qk);
    (void)lut;
    for (ptrdiff_t b = 0; b * block < n; b++) {
        ptrdiff_t lo = b * block;
        ptrdiff_t hi = lo + block < n ? lo + block : n;
        ptrdiff_t bn = hi - lo;
        float s = scales[b];
#if BSC_X86
        if (lvl == 2 && bn >= 8) {
            bsc_dec_avx2(lut, q + lo, s, out + lo, bn);
            continue;
        }
        if (lvl >= 1 && bn >= 4) {
            bsc_dec_sse2(lut, q + lo, s, out + lo, bn);
            continue;
        }
#endif
        bsc_dec_scalar(qk, q + lo, s, out + lo, bn);
    }
}

static inline int bsc_combine(int func, int qk, ptrdiff_t block,
                              const float *scales, const uint8_t *q,
                              const float *other, float *out, ptrdiff_t n) {
    if (func < BSC_F_SUM || func > BSC_F_PROD)
        return -1;
    int lvl = bsc_level();
    const float *lut = bsc_lut(qk);
    (void)lut;
    for (ptrdiff_t b = 0; b * block < n; b++) {
        ptrdiff_t lo = b * block;
        ptrdiff_t hi = lo + block < n ? lo + block : n;
        ptrdiff_t bn = hi - lo;
        float s = scales[b];
#if BSC_X86
        if (lvl == 2 && bn >= 8) {
            bsc_comb_avx2(func, lut, q + lo, s, other + lo, out + lo, bn);
            continue;
        }
        if (lvl >= 1 && bn >= 4) {
            bsc_comb_sse2(func, lut, q + lo, s, other + lo, out + lo, bn);
            continue;
        }
#endif
        if (bsc_comb_scalar(func, qk, q + lo, s, other + lo, out + lo, bn))
            return -1;
    }
    return 0;
}

#endif /* ACCL_BS_CODEC_H */
