// C++ driver demo/acceptance test: one process per rank, driving its rank
// daemon through the full op surface with validation.
//
// Role parity with the reference XRT demo main (driver/xrt/src/main.cpp:
// 34-100 — per-stage Timer microbenchmarks and a nop) plus the hardware
// test program's per-collective validation style (test/host/test.py).
//
//   ./cclo_emud --rank R --world W --port-base P   (per rank, then)
//   ./accl_demo --rank R --world W --port-base P
//
// Prints per-stage timings and "rank R: all tests succeeded" on success;
// exits nonzero on any mismatch (greppable by the orchestrator).

#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <string>
#include <vector>

#include "accl_driver.hpp"

using accl::ACCL;
using accl::Buffer;
using accl::Timer;
using namespace accl_proto;

static int failures = 0;

static void expect_near(const std::vector<float>& got, float want,
                        const char* what, size_t lo = 0,
                        size_t hi = SIZE_MAX) {
  if (hi == SIZE_MAX) hi = got.size();
  for (size_t i = lo; i < hi; ++i) {
    if (std::fabs(got[i] - want) > 1e-4f * std::fabs(want) + 1e-5f) {
      std::fprintf(stderr, "FAIL %s: [%zu] = %g, want %g\n", what, i,
                   got[i], want);
      ++failures;
      return;
    }
  }
}

// Pure-native chained-call benchmark (reference test.py:934-950 in C++):
// isolated nop p50 vs per-link cost of a DEPTH-deep pipelined chain,
// interleaved like benchmarks/chained.py so drift hits both equally.
static int chain_bench(ACCL& a, size_t depth, int reps) {
  auto p50 = [](std::vector<double>& v) {
    std::sort(v.begin(), v.end());
    return v[v.size() / 2];
  };
  for (int i = 0; i < 8; ++i) a.nop();  // warmup
  std::vector<double> iso, link;
  std::vector<ACCL::CallSpec> nops(depth);
  for (auto& s : nops) { s = ACCL::CallSpec{}; s.scenario = OP_NOP; }
  for (int r = 0; r < reps; ++r) {
    for (int i = 0; i < 4; ++i) {
      Timer t; t.start(); a.nop(); t.end();
      iso.push_back(static_cast<double>(t.elapsed_us()));
    }
    Timer t; t.start();
    auto ids = a.call_chain(nops);
    a.wait(ids.back(), 20.0);
    t.end();
    link.push_back(static_cast<double>(t.elapsed_us()) /
                   static_cast<double>(depth));
  }
  std::printf("native-driver     isolated %8.1f us   chained/link "
              "%8.1f us   ratio %.2f\n",
              p50(iso), p50(link), p50(link) / p50(iso));
  return 0;
}

int main(int argc, char** argv) {
  uint32_t rank = 0, world = 2;
  uint16_t port_base = 45000;
  size_t bench_depth = 0;
  int bench_reps = 30;
  for (int i = 1; i + 1 < argc; i += 2) {
    std::string k = argv[i];
    const char* v = argv[i + 1];
    if (k == "--rank") rank = atoi(v);
    else if (k == "--world") world = atoi(v);
    else if (k == "--port-base") port_base = atoi(v);
    else if (k == "--chain-bench") bench_depth = atoi(v);
    else if (k == "--reps") bench_reps = atoi(v);
  }

  Timer t_construct, t_config, t_nop, t_collectives;

  t_construct.start();
  ACCL a("127.0.0.1", static_cast<uint16_t>(port_base + rank));
  t_construct.end();

  t_config.start();
  a.configure_communicator(
      accl::world_communicator(0xACC1u, world, rank, port_base));
  a.set_timeout(20.0);
  t_config.end();

  t_nop.start();
  a.nop();
  t_nop.end();

  if (bench_depth) return chain_bench(a, bench_depth, bench_reps);

  const uint64_t N = 64;  // elements per rank
  t_collectives.start();

  // copy + combine (local dataplane)
  {
    Buffer src = a.alloc(N), dst = a.alloc(N), sum = a.alloc(N);
    std::vector<float> v(N, 3.0f + rank);
    a.write(src, v.data());
    a.copy(src, dst, N);
    expect_near(a.read_vec<float>(dst), 3.0f + rank, "copy");
    a.combine(N, FN_SUM, src, dst, sum);
    expect_near(a.read_vec<float>(sum), 2 * (3.0f + rank), "combine");
    a.free(src); a.free(dst); a.free(sum);
  }

  // pipelined wire-waitfor chain (ap_ctrl_chain parity): a 16-deep
  // combine chain whose operand is always the previous link's result —
  // acc doubles per link, submitted in ONE coalesced write
  {
    const int depth = 16;
    Buffer acc = a.alloc(N);
    std::vector<float> v1(N, 1.0f);
    a.write(acc, v1.data());
    std::vector<ACCL::CallSpec> links;
    for (int i = 0; i < depth; ++i) {
      ACCL::CallSpec s{};
      s.scenario = OP_COMBINE;
      s.count = N;
      s.func = FN_SUM;
      s.addr0 = acc.addr;
      s.addr1 = acc.addr;
      s.addr2 = acc.addr;
      links.push_back(s);
    }
    auto ids = a.call_chain(links);
    a.wait(ids.back(), 20.0);
    expect_near(a.read_vec<float>(acc),
                static_cast<float>(1 << depth), "call_chain");
    a.free(acc);

    // deep chain crossing the CHUNK boundary (600 > 2x256): later
    // chunks hook their first link to the previous chunk's last id by
    // explicit waitfor — retiring the final id retires all 600 links
    std::vector<ACCL::CallSpec> nops(600);
    for (auto& s : nops) { s = ACCL::CallSpec{}; s.scenario = OP_NOP; }
    auto nids = a.call_chain(nops);
    if (nids.size() != 600) {
      std::fprintf(stderr, "FAIL call_chain(deep): %zu ids\n",
                   nids.size());
      ++failures;
    }
    a.wait(nids.back(), 20.0);
  }

  // tag-matched send/recv ping-pong rank 0 <-> 1
  if (world >= 2 && rank < 2) {
    Buffer buf = a.alloc(N);
    if (rank == 0) {
      std::vector<float> v(N, 7.5f);
      a.write(buf, v.data());
      a.send(buf, N, 1, 42);
      a.recv(buf, N, 1, 43);
      expect_near(a.read_vec<float>(buf), -2.5f, "pingpong(0)");
    } else {
      a.recv(buf, N, 0, 42);
      expect_near(a.read_vec<float>(buf), 7.5f, "pingpong(1) recv");
      std::vector<float> v(N, -2.5f);
      a.write(buf, v.data());
      a.send(buf, N, 0, 43);
    }
    a.free(buf);
  }
  a.barrier();

  // bcast from each root in turn
  for (uint32_t root = 0; root < world; ++root) {
    Buffer buf = a.alloc(N);
    std::vector<float> v(N, rank == root ? 100.0f + root : 0.0f);
    a.write(buf, v.data());
    a.bcast(buf, N, root);
    expect_near(a.read_vec<float>(buf), 100.0f + root, "bcast");
    a.free(buf);
  }

  // allreduce (sum of rank+1 = W(W+1)/2)
  {
    Buffer src = a.alloc(N), dst = a.alloc(N);
    std::vector<float> v(N, static_cast<float>(rank + 1));
    a.write(src, v.data());
    a.allreduce(src, dst, N);
    expect_near(a.read_vec<float>(dst),
                world * (world + 1) / 2.0f, "allreduce");
    // compressed wire (fp16 lanes)
    a.allreduce(src, dst, N, FN_SUM, DT_F16);
    expect_near(a.read_vec<float>(dst),
                world * (world + 1) / 2.0f, "allreduce(fp16 wire)");
    // algorithm variants (xlnx-consts ring/rr/fused axis)
    a.allreduce(src, dst, N, FN_SUM, 0xFF, ALG_NON_FUSED);
    expect_near(a.read_vec<float>(dst),
                world * (world + 1) / 2.0f, "allreduce(non-fused)");
    a.free(src); a.free(dst);
  }

  // tree bcast + direct gather/allgather variants
  {
    Buffer buf = a.alloc(N);
    std::vector<float> v(N, rank == 1 ? 77.0f : 0.0f);
    a.write(buf, v.data());
    a.bcast(buf, N, 1, ALG_TREE);
    expect_near(a.read_vec<float>(buf), 77.0f, "bcast(tree)");
    Buffer dst = a.alloc(world * N);
    std::vector<float> mine(N, static_cast<float>(rank + 5));
    a.write(buf, mine.data());
    a.allgather(buf, dst, N, ALG_ROUND_ROBIN);
    auto got = a.read_vec<float>(dst);
    for (uint32_t r = 0; r < world; ++r)
      expect_near(got, static_cast<float>(r + 5), "allgather(rr)",
                  r * N, (r + 1) * N);
    a.free(buf); a.free(dst);
  }

  // reduce to root 0, max
  {
    Buffer src = a.alloc(N), dst = a.alloc(N);
    std::vector<float> v(N, static_cast<float>(rank * 2));
    a.write(src, v.data());
    a.reduce(src, dst, N, 0, FN_MAX);
    if (rank == 0)
      expect_near(a.read_vec<float>(dst), 2.0f * (world - 1),
                  "reduce(max)");
    a.free(src); a.free(dst);
  }

  // scatter/gather round trip via root 0
  {
    Buffer big = a.alloc(N * world), mine = a.alloc(N),
           back = a.alloc(N * world);
    if (rank == 0) {
      std::vector<float> v(N * world);
      for (uint64_t i = 0; i < N * world; ++i)
        v[i] = static_cast<float>(i / N);  // chunk r holds value r
      a.write(big, v.data());
    }
    a.scatter(big, mine, N, 0);
    expect_near(a.read_vec<float>(mine), static_cast<float>(rank),
                "scatter");
    a.gather(mine, back, N, 0);
    if (rank == 0) {
      auto v = a.read_vec<float>(back);
      for (uint32_t r = 0; r < world; ++r)
        expect_near(v, static_cast<float>(r), "gather", r * N,
                    (r + 1) * N);
    }
    a.free(big); a.free(mine); a.free(back);
  }

  // allgather + reduce_scatter
  {
    Buffer chunk = a.alloc(N), all = a.alloc(N * world);
    std::vector<float> v(N, static_cast<float>(10 + rank));
    a.write(chunk, v.data());
    a.allgather(chunk, all, N);
    auto got = a.read_vec<float>(all);
    for (uint32_t r = 0; r < world; ++r)
      expect_near(got, static_cast<float>(10 + r), "allgather", r * N,
                  (r + 1) * N);

    Buffer big = a.alloc(N * world), red = a.alloc(N);
    std::vector<float> w(N * world);
    for (uint64_t i = 0; i < N * world; ++i)
      w[i] = static_cast<float>(i / N + 1);  // chunk r = r+1 everywhere
    a.write(big, w.data());
    a.reduce_scatter(big, red, N);
    expect_near(a.read_vec<float>(red),
                static_cast<float>((rank + 1) * world), "reduce_scatter");
    a.free(chunk); a.free(all); a.free(big); a.free(red);
  }

  // alltoall
  {
    Buffer src = a.alloc(N * world), dst = a.alloc(N * world);
    std::vector<float> v(N * world);
    for (uint64_t i = 0; i < N * world; ++i)
      v[i] = static_cast<float>(rank * 1000 + i / N);  // chunk d: my row d
    a.write(src, v.data());
    a.alltoall(src, dst, N);
    auto got = a.read_vec<float>(dst);
    for (uint32_t r = 0; r < world; ++r)
      expect_near(got, static_cast<float>(r * 1000 + rank), "alltoall",
                  r * N, (r + 1) * N);
    a.free(src); a.free(dst);
  }

  // stream ports: remote-stream put -> peer OP0_STREAM copy; local
  // push -> OP0_STREAM copy; RES_STREAM copy -> stream_pop
  if (world >= 2 && rank < 2) {
    if (rank == 0) {
      Buffer sbuf = a.alloc(N);
      std::vector<float> v(N, 55.0f);
      a.write(sbuf, v.data());
      a.stream_put(sbuf, N, /*dst=*/1);
      a.free(sbuf);
    } else {
      Buffer dbuf = a.alloc(N);
      a.copy_from_stream(dbuf, N);
      expect_near(a.read_vec<float>(dbuf), 55.0f, "stream_put->op0_stream");
      a.free(dbuf);
    }
    // local in-port: host push -> OP0_STREAM copy
    std::vector<float> loop(N, 9.25f + rank);
    a.stream_push(loop.data(), N * 4, DT_F32);
    Buffer lbuf = a.alloc(N);
    a.copy_from_stream(lbuf, N);
    expect_near(a.read_vec<float>(lbuf), 9.25f + rank, "stream_push->copy");
    // out-port: RES_STREAM copy -> counted stream_pop
    a.copy_to_stream(lbuf, N);
    uint8_t dt = 0;
    auto raw = a.stream_pop(10.0, N, &dt);
    if (dt != DT_F32 || raw.size() != N * sizeof(float)) {
      std::fprintf(stderr, "FAIL res_stream->pop: dtype %u size %zu\n",
                   dt, raw.size());
      ++failures;
    } else {
      std::vector<float> got(N);
      std::memcpy(got.data(), raw.data(), raw.size());
      expect_near(got, 9.25f + rank, "res_stream->pop");
    }
    a.free(lbuf);
  }
  a.barrier();

  // error path: recv with no matching send must raise RECEIVE_TIMEOUT
  {
    a.set_timeout(0.2);
    Buffer buf = a.alloc(4);
    bool threw = false;
    try {
      a.recv(buf, 4, (rank + 1) % world, 777);
    } catch (const accl::ACCLError& e) {
      threw = (e.error_word & E_RECV_TIMEOUT) != 0;
    }
    if (!threw) {
      std::fprintf(stderr, "FAIL timeout: no RECEIVE_TIMEOUT_ERROR\n");
      ++failures;
    }
    a.set_timeout(20.0);
    a.free(buf);
  }
  a.barrier();
  t_collectives.end();

  std::printf("rank %u: t_construct=%lu us t_config=%lu us t_nop=%lu us "
              "t_collectives=%lu us\n", rank, t_construct.elapsed_us(),
              t_config.elapsed_us(), t_nop.elapsed_us(),
              t_collectives.elapsed_us());
  if (failures) {
    std::fprintf(stderr, "rank %u: %d FAILURES\n", rank, failures);
    return 1;
  }
  std::printf("rank %u: all tests succeeded\n", rank);
  return 0;
}
