// accl_tpu C++ host driver.
//
// Role parity with the reference's XRT C++ driver (driver/xrt/: ACCL
// class in xlnx-device.hpp:48-235, communicator in xlnx-comm.hpp:32-82,
// Timer in timing.hpp:25-53) — but complete rather than WIP: the full
// primitive/collective surface of the Python driver (accl_tpu/accl.py),
// sync + async call forms, buffer management, error decode and
// introspection, speaking the framed-TCP protocol (protocol.hpp) to a
// rank daemon (cclo_emud or the Python daemon — they are
// indistinguishable on the wire).
//
// Header-only; link only needs -pthread.

#pragma once

#include <arpa/inet.h>
#include <netinet/tcp.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <cstdint>
#include <mutex>
#include <stdexcept>
#include <string>
#include <thread>
#include <vector>

#include "protocol.hpp"

namespace accl {

using namespace accl_proto;

// wire sentinel: "the previous MSG_CALL on this connection" (both
// daemons resolve it per connection; protocol.py WAITFOR_PREV)
static const uint32_t WAITFOR_PREV = 0xFFFFFFFFu;

// Timer parity: driver/xrt/include/timing.hpp
class Timer {
 public:
  void start() { start_ = clock_::now(); started_ = true; }
  void end() { end_ = clock_::now(); ended_ = true; }
  unsigned long elapsed_us() const {
    if (!started_ || !ended_) return 0;
    return static_cast<unsigned long>(
        std::chrono::duration_cast<std::chrono::microseconds>(end_ - start_)
            .count());
  }

 private:
  using clock_ = std::chrono::steady_clock;
  clock_::time_point start_, end_;
  bool started_ = false, ended_ = false;
};

struct RankSpec {
  std::string host;
  uint16_t port;       // the rank daemon's CMD port; daemons derive the
                       // eth port themselves as cmd port + world
  uint32_t global_rank;
};

struct Communicator {
  uint32_t comm_id;
  uint32_t local_rank;
  std::vector<RankSpec> ranks;
  uint32_t size() const { return static_cast<uint32_t>(ranks.size()); }
};

struct Buffer {
  uint64_t addr = 0;
  uint64_t count = 0;
  uint8_t dtype = DT_F32;
  uint64_t nbytes() const { return count * dtype_size(dtype); }
};

class ACCLError : public std::runtime_error {
 public:
  ACCLError(uint32_t err, const std::string& what)
      : std::runtime_error(what), error_word(err) {}
  uint32_t error_word;
};

inline std::string decode_error(uint32_t err) {
  if (err == E_OK) return "success";
  std::string s;
  auto add = [&](uint32_t bit, const char* name) {
    if (err & bit) { if (!s.empty()) s += "|"; s += name; }
  };
  add(E_DMA_MISMATCH, "DMA_MISMATCH_ERROR");
  add(E_RECV_TIMEOUT, "RECEIVE_TIMEOUT_ERROR");
  add(E_DMA_SIZE, "DMA_SIZE_ERROR");
  add(E_COMM_NOT_CONFIGURED, "COMM_NOT_CONFIGURED");
  add(E_SPARE_OVERFLOW, "SPARE_BUFFER_OVERFLOW");
  add(E_INVALID, "INVALID_CALL");
  return s.empty() ? "error 0x" + std::to_string(err) : s;
}

// One rank's handle to its daemon: the C++ `accl` class.
class ACCL {
 public:
  ACCL(const std::string& host, uint16_t cmd_port,
       double connect_timeout_s = 10.0) {
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::duration<double>(connect_timeout_s);
    while (true) {
      fd_ = try_connect(host, cmd_port);
      if (fd_ >= 0) break;
      if (std::chrono::steady_clock::now() >= deadline)
        throw std::runtime_error("cannot connect to rank daemon at " +
                                 host + ":" + std::to_string(cmd_port));
      std::this_thread::sleep_for(std::chrono::milliseconds(100));
    }
    ping();
  }

  ~ACCL() {
    if (fd_ >= 0) ::close(fd_);
  }

  ACCL(const ACCL&) = delete;
  ACCL& operator=(const ACCL&) = delete;

  // -- lifecycle ----------------------------------------------------------
  void configure_communicator(const Communicator& comm) {
    std::vector<uint8_t> body{MSG_CONFIG_COMM};
    put_le<uint32_t>(body, comm.comm_id);
    put_le<uint32_t>(body, comm.local_rank);
    put_le<uint32_t>(body, comm.size());
    for (const auto& r : comm.ranks) {
      put_le<uint32_t>(body, r.global_rank);
      put_le<uint16_t>(body, r.port);
      put_le<uint16_t>(body, static_cast<uint16_t>(r.host.size()));
      body.insert(body.end(), r.host.begin(), r.host.end());
    }
    check(body);
    comm_ = comm;
  }

  const Communicator& comm() const { return comm_; }
  uint32_t rank() const { return comm_.local_rank; }
  uint32_t world_size() const { return comm_.size(); }

  void set_timeout(double seconds) {
    std::vector<uint8_t> body{MSG_SET_TIMEOUT};
    put_le<double>(body, seconds);
    check(body);
  }

  void set_max_segment_size(uint64_t nbytes) {
    std::vector<uint8_t> body{MSG_SET_SEG};
    put_le<uint64_t>(body, nbytes);
    check(body);
  }

  void ping() { check({MSG_PING}); }
  void soft_reset() { check({MSG_RESET}); }

  std::string dump_rx_buffers() {
    auto reply = request({MSG_DUMP_RX});
    return std::string(reply.begin() + 1, reply.end());
  }

  // -- buffers (4 KiB-aligned bump allocator, SimBuffer parity) -----------
  Buffer alloc(uint64_t count, uint8_t dtype = DT_F32) {
    Buffer b;
    b.count = count;
    b.dtype = dtype;
    uint64_t nbytes = b.nbytes();
    {
      std::lock_guard<std::mutex> lk(alloc_mu_);
      b.addr = next_addr_;
      next_addr_ += ((nbytes + 4095) / 4096 + 1) * 4096;
    }
    std::vector<uint8_t> body{MSG_ALLOC};
    put_le<uint64_t>(body, b.addr);
    put_le<uint64_t>(body, nbytes);
    check(body);
    return b;
  }

  void free(const Buffer& b) {
    std::vector<uint8_t> body{MSG_FREE};
    put_le<uint64_t>(body, b.addr);
    check(body);
  }

  void write(const Buffer& b, const void* data, uint64_t nbytes = 0) {
    if (!nbytes) nbytes = b.nbytes();
    std::vector<uint8_t> body{MSG_WRITE_MEM};
    put_le<uint64_t>(body, b.addr);
    const uint8_t* p = static_cast<const uint8_t*>(data);
    body.insert(body.end(), p, p + nbytes);
    check(body);
  }

  void read(const Buffer& b, void* data, uint64_t nbytes = 0) {
    if (!nbytes) nbytes = b.nbytes();
    std::vector<uint8_t> body{MSG_READ_MEM};
    put_le<uint64_t>(body, b.addr);
    put_le<uint64_t>(body, nbytes);
    auto reply = request(body);
    if (reply.empty() || reply[0] != MSG_DATA || reply.size() - 1 < nbytes)
      throw std::runtime_error("short MSG_READ_MEM reply");
    std::memcpy(data, reply.data() + 1, nbytes);
  }

  template <typename T>
  std::vector<T> read_vec(const Buffer& b) {
    std::vector<T> out(b.count);
    read(b, out.data(), b.count * sizeof(T));
    return out;
  }

  // -- calls --------------------------------------------------------------
  // One call's descriptor fields; the building block of chained
  // submission (the Python driver's CallDescriptor analog).
  struct CallSpec {
    uint8_t scenario;
    uint64_t count = 0;
    uint32_t root = 0;
    uint8_t func = 0;
    uint32_t tag = TAG_ANY;
    uint64_t addr0 = 0, addr1 = 0, addr2 = 0;
    uint8_t udtype = DT_F32, cdtype = DT_F32;
    uint8_t compression = C_NONE;
    uint8_t stream = 0;
    uint8_t algorithm = ALG_AUTO;
  };

  // Async form: returns a call id; wait(id) blocks until retirement.
  // ``waitfor`` ships wire dependency ids (earlier call ids, or
  // WAITFOR_PREV for "the previous call on this connection") — the
  // daemon's FIFO worker enforces ordering and error propagation.
  uint32_t call_async(uint8_t scenario, uint64_t count, uint32_t root,
                      uint8_t func, uint32_t tag, uint64_t addr0,
                      uint64_t addr1, uint64_t addr2, uint8_t udtype,
                      uint8_t cdtype, uint8_t compression = C_NONE,
                      uint8_t stream = 0, uint8_t algorithm = ALG_AUTO,
                      const std::vector<uint32_t>& waitfor = {}) {
    CallSpec s{scenario, count, root, func, tag, addr0, addr1, addr2,
               udtype, cdtype, compression, stream, algorithm};
    auto reply = request(build_call(s, waitfor));
    if (reply.empty() || reply[0] != MSG_CALL_ID)
      throw std::runtime_error("bad MSG_CALL reply");
    return get_le<uint32_t>(reply.data() + 1);
  }

  // Pipelined chain submission (hostctrl ap_ctrl_chain parity,
  // reference hostctrl.cpp:56-90; the Python driver's batched
  // wire-waitfor path, device/sim.py _flush_run): every link after the
  // first carries WAITFOR_PREV, ALL the MSG_CALL frames leave in one
  // coalesced write, and the CALL_ID replies stream back — an N-deep
  // chain costs N pipelined submissions, not N serialized round trips.
  // Returns the call ids; wait(ids.back()) retires the whole chain
  // (FIFO retirement + daemon-side failed-dep propagation).
  std::vector<uint32_t> call_chain(const std::vector<CallSpec>& links) {
    // Chunked submission: writing an unbounded batch before reading any
    // reply can deadlock once both TCP directions fill (the daemon
    // blocks writing CALL_ID replies the client isn't reading). Each
    // chunk's replies drain before the next chunk ships; the first link
    // of a later chunk names its dependency by EXPLICIT id — its true
    // predecessor's id is already known from the drained replies.
    static const size_t CHUNK = 256;
    std::vector<uint32_t> ids;
    std::lock_guard<std::mutex> lk(io_mu_);
    for (size_t base = 0; base < links.size(); base += CHUNK) {
      size_t n = std::min(CHUNK, links.size() - base);
      std::vector<std::vector<uint8_t>> frames;
      frames.reserve(n);
      for (size_t i = 0; i < n; ++i) {
        std::vector<uint32_t> wf;
        if (i)
          wf.push_back(WAITFOR_PREV);
        else if (base)
          wf.push_back(ids.back());
        frames.push_back(build_call(links[base + i], wf));
      }
      if (!send_frames(fd_, frames))
        throw std::runtime_error("daemon connection closed (send)");
      for (size_t i = 0; i < n; ++i) {
        std::vector<uint8_t> reply;
        if (!recv_frame(fd_, reply))
          throw std::runtime_error("daemon connection closed (recv)");
        if (reply.empty() || reply[0] != MSG_CALL_ID)
          throw std::runtime_error("bad MSG_CALL reply in chain");
        ids.push_back(get_le<uint32_t>(reply.data() + 1));
      }
    }
    return ids;
  }

  void wait(uint32_t call_id, double budget_s = 0.05) {
    while (true) {
      std::vector<uint8_t> body{MSG_WAIT};
      put_le<uint32_t>(body, call_id);
      put_le<double>(body, budget_s);
      uint32_t err = request_status(body);
      if (err == STATUS_PENDING) continue;
      if (err != E_OK)
        throw ACCLError(err, "call " + std::to_string(call_id) +
                                 " failed: " + decode_error(err));
      return;
    }
  }

  // -- primitives (Python accl.py surface) --------------------------------
  void nop() { wait(call_async(OP_NOP, 0, 0, 0, 0, 0, 0, 0, DT_F32, DT_F32)); }

  void copy(const Buffer& src, const Buffer& dst, uint64_t count) {
    wait(call_async(OP_COPY, count, 0, 0, 0, src.addr, 0, dst.addr,
                    src.dtype, src.dtype));
  }

  void combine(uint64_t count, uint8_t func, const Buffer& op0,
               const Buffer& op1, const Buffer& res) {
    wait(call_async(OP_COMBINE, count, 0, func, 0, op0.addr, op1.addr,
                    res.addr, op0.dtype, op0.dtype));
  }

  void send(const Buffer& src, uint64_t count, uint32_t dst, uint32_t tag,
            uint8_t wire_dtype = 0xFF) {
    uint8_t cd = wire_dtype == 0xFF ? src.dtype : wire_dtype;
    uint8_t comp = cd != src.dtype ? C_ETH : C_NONE;
    wait(call_async(OP_SEND, count, dst, 0, tag, src.addr, 0, 0, src.dtype,
                    cd, comp));
  }

  void recv(const Buffer& dst, uint64_t count, uint32_t src, uint32_t tag,
            uint8_t wire_dtype = 0xFF) {
    uint8_t cd = wire_dtype == 0xFF ? dst.dtype : wire_dtype;
    uint8_t comp = cd != dst.dtype ? C_ETH : C_NONE;
    wait(call_async(OP_RECV, count, src, 0, tag, 0, 0, dst.addr, dst.dtype,
                    cd, comp));
  }

  // -- collectives --------------------------------------------------------
  // Each takes an optional algorithm selector (Alg enum — the reference
  // XRT driver's ring/rr/fused variant axis, xlnx-consts.hpp:43-66).
  void bcast(const Buffer& buf, uint64_t count, uint32_t root,
             uint8_t alg = ALG_AUTO) {
    wait(call_async(OP_BCAST, count, root, 0, TAG_ANY, buf.addr, 0, 0,
                    buf.dtype, buf.dtype, C_NONE, 0, alg));
  }

  void scatter(const Buffer& src, const Buffer& dst, uint64_t count,
               uint32_t root) {
    wait(call_async(OP_SCATTER, count, root, 0, TAG_ANY, src.addr, 0,
                    dst.addr, dst.dtype, dst.dtype));
  }

  void gather(const Buffer& src, const Buffer& dst, uint64_t count,
              uint32_t root, uint8_t alg = ALG_AUTO) {
    wait(call_async(OP_GATHER, count, root, 0, TAG_ANY, src.addr, 0,
                    dst.addr, src.dtype, src.dtype, C_NONE, 0, alg));
  }

  void reduce(const Buffer& src, const Buffer& dst, uint64_t count,
              uint32_t root, uint8_t func = FN_SUM,
              uint8_t alg = ALG_AUTO) {
    wait(call_async(OP_REDUCE, count, root, func, TAG_ANY, src.addr, 0,
                    dst.addr, src.dtype, src.dtype, C_NONE, 0, alg));
  }

  void allgather(const Buffer& src, const Buffer& dst, uint64_t count,
                 uint8_t alg = ALG_AUTO) {
    wait(call_async(OP_ALLGATHER, count, 0, 0, TAG_ANY, src.addr, 0,
                    dst.addr, src.dtype, src.dtype, C_NONE, 0, alg));
  }

  void allreduce(const Buffer& src, const Buffer& dst, uint64_t count,
                 uint8_t func = FN_SUM, uint8_t wire_dtype = 0xFF,
                 uint8_t alg = ALG_AUTO) {
    uint8_t cd = wire_dtype == 0xFF ? src.dtype : wire_dtype;
    uint8_t comp = cd != src.dtype ? C_ETH : C_NONE;
    wait(call_async(OP_ALLREDUCE, count, 0, func, TAG_ANY, src.addr, 0,
                    dst.addr, src.dtype, cd, comp, 0, alg));
  }

  void reduce_scatter(const Buffer& src, const Buffer& dst, uint64_t count,
                      uint8_t func = FN_SUM) {
    wait(call_async(OP_REDUCE_SCATTER, count, 0, func, TAG_ANY, src.addr,
                    0, dst.addr, src.dtype, src.dtype));
  }

  void alltoall(const Buffer& src, const Buffer& dst, uint64_t count) {
    wait(call_async(OP_ALLTOALL, count, 0, 0, TAG_ANY, src.addr, 0,
                    dst.addr, src.dtype, src.dtype));
  }

  void barrier() {
    wait(call_async(OP_BARRIER, 1, 0, 0, TAG_ANY, 0, 0, 0, DT_F32,
                    DT_F32));
  }

  // -- external-kernel stream ports ---------------------------------------
  // stream_put: send into the PEER's stream port (remote-stream send,
  // strm=1 on the wire); stream_push/stream_pop: this rank's local
  // stream-in/stream-out ports (MSG_STREAM_PUSH/POP). pop polls with
  // short budgets like wait() so the command socket is never monopolized.
  void stream_put(const Buffer& src, uint64_t count, uint32_t dst,
                  uint32_t tag = TAG_ANY) {
    wait(call_async(OP_SEND, count, dst, 0, tag, src.addr, 0, 0, src.dtype,
                    src.dtype, C_NONE, /*stream=*/2));
  }

  // OP0_STREAM copy: materialize `count` stream-in elements into dst
  void copy_from_stream(const Buffer& dst, uint64_t count) {
    wait(call_async(OP_COPY, count, 0, 0, 0, 0, 0, dst.addr, dst.dtype,
                    dst.dtype, C_NONE, /*stream=*/1));
  }

  // RES_STREAM copy: src buffer onto the local stream-out port
  void copy_to_stream(const Buffer& src, uint64_t count) {
    wait(call_async(OP_COPY, count, 0, 0, 0, src.addr, 0, 0, src.dtype,
                    src.dtype, C_NONE, /*stream=*/2));
  }

  void stream_push(const void* data, uint64_t nbytes, uint8_t dtype) {
    std::vector<uint8_t> body{MSG_STREAM_PUSH, dtype};
    const uint8_t* p = static_cast<const uint8_t*>(data);
    body.insert(body.end(), p, p + nbytes);
    check(body);
  }

  // returns the payload bytes and writes the element dtype to *dtype_out;
  // count = 0 pops the next produced entry whole
  std::vector<uint8_t> stream_pop(double timeout_s, uint64_t count = 0,
                                  uint8_t* dtype_out = nullptr) {
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::duration<double>(timeout_s);
    for (;;) {
      std::vector<uint8_t> body{MSG_STREAM_POP};
      double budget = 0.05;
      put_le<double>(body, budget);
      put_le<uint64_t>(body, count);
      auto reply = request(body);
      if (reply.size() >= 2 && reply[0] == MSG_DATA) {
        if (dtype_out) *dtype_out = reply[1];
        return std::vector<uint8_t>(reply.begin() + 2, reply.end());
      }
      // decode statuses like wait(): only STATUS_PENDING means retry —
      // a real error must surface, not be spun on until a bogus timeout
      if (reply.size() >= 5 && reply[0] == MSG_STATUS) {
        uint32_t err = get_le<uint32_t>(reply.data() + 1);
        if (err != STATUS_PENDING)
          throw ACCLError(err, "stream_pop");
      }
      if (std::chrono::steady_clock::now() >= deadline)
        throw ACCLError(E_RECV_TIMEOUT, "stream-out port empty");
    }
  }

  void shutdown_daemon() { check({MSG_SHUTDOWN}); }

 private:
  static int try_connect(const std::string& host, uint16_t port) {
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(port);
    if (::inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
      ::close(fd);
      return -1;
    }
    if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
      ::close(fd);
      return -1;
    }
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    return fd;
  }

  static std::vector<uint8_t> build_call_body(
      const CallSpec& s, const std::vector<uint32_t>& waitfor,
      uint32_t comm_id) {
    std::vector<uint8_t> body{MSG_CALL};
    put_le<uint8_t>(body, s.scenario);
    put_le<uint8_t>(body, s.func);
    put_le<uint8_t>(body, s.compression);
    put_le<uint8_t>(body, s.stream);
    put_le<uint8_t>(body, s.udtype);
    put_le<uint8_t>(body, s.cdtype);
    put_le<uint8_t>(body, s.algorithm);
    put_le<uint8_t>(body, 0);  // pad
    put_le<uint64_t>(body, s.count);
    put_le<uint32_t>(body, comm_id);
    put_le<uint32_t>(body, s.root);
    put_le<uint32_t>(body, s.tag);
    put_le<uint64_t>(body, s.addr0);
    put_le<uint64_t>(body, s.addr1);
    put_le<uint64_t>(body, s.addr2);
    put_le<uint16_t>(body, static_cast<uint16_t>(waitfor.size()));
    for (uint32_t w : waitfor) put_le<uint32_t>(body, w);
    return body;
  }

  std::vector<uint8_t> build_call(const CallSpec& s,
                                  const std::vector<uint32_t>& waitfor) {
    return build_call_body(s, waitfor, comm_.comm_id);
  }

  std::vector<uint8_t> request(const std::vector<uint8_t>& body) {
    std::lock_guard<std::mutex> lk(io_mu_);
    if (!send_frame(fd_, body))
      throw std::runtime_error("daemon connection closed (send)");
    std::vector<uint8_t> reply;
    if (!recv_frame(fd_, reply))
      throw std::runtime_error("daemon connection closed (recv)");
    return reply;
  }

  uint32_t request_status(const std::vector<uint8_t>& body) {
    auto reply = request(body);
    if (reply.size() < 5 || reply[0] != MSG_STATUS)
      throw std::runtime_error("bad status reply");
    return get_le<uint32_t>(reply.data() + 1);
  }

  void check(const std::vector<uint8_t>& body) {
    uint32_t err = request_status(body);
    if (err != E_OK) throw ACCLError(err, decode_error(err));
  }

  int fd_ = -1;
  std::mutex io_mu_;
  std::mutex alloc_mu_;
  uint64_t next_addr_ = 4096;
  Communicator comm_;
};

// Convenience: a world communicator over daemons at port_base..+W-1, with
// eth ports at port_base+W.. (the daemon spawn convention).
inline Communicator world_communicator(uint32_t comm_id, uint32_t world,
                                       uint32_t local_rank,
                                       uint16_t port_base,
                                       const std::string& host =
                                           "127.0.0.1") {
  Communicator c;
  c.comm_id = comm_id;
  c.local_rank = local_rank;
  for (uint32_t r = 0; r < world; ++r)
    c.ranks.push_back({host, static_cast<uint16_t>(port_base + r), r});
  return c;
}

}  // namespace accl
