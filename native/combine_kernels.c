/* _accl_combine: contiguous two-operand elementwise reduction kernels.
 *
 * The CPU-native twin of the reference's per-dtype reduce_sum plugins
 * (kernels/plugins/reduce_sum): one compiled loop per (func, dtype) over
 * contiguous spans, exposed to Python through one METH_FASTCALL entry so
 * the emulator's combine workers stop paying numpy's per-segment ufunc
 * dispatch (~0.5-1us per call — comparable to the whole memory op at the
 * 4-64 KiB segment sizes the streamed executor feeds them).
 *
 * Contract (enforced by accl_tpu/native_combine.py, the loader):
 *   - results are BIT-IDENTICAL to the numpy fallback for every
 *     supported (func, dtype): float ops use the same IEEE single/double
 *     arithmetic; f16/bf16 compute in float32 (both operands are exactly
 *     representable there, so the sum/product is exact) and round back
 *     with the same round-to-nearest-even numpy/ml_dtypes use; integer
 *     SUM/PROD wrap modulo 2^n via unsigned arithmetic (signed overflow
 *     is UB in C, defined wraparound in numpy); MAX/MIN mirror numpy's
 *     `(a > b || isnan(a)) ? a : b` (strict compare: the SECOND operand
 *     wins ties, visible on signed zeros; NaN in either propagates).
 *   - dtype codes are accl_tpu/emulator/protocol.py DTYPE_CODES; func
 *     codes are accl_tpu.constants.ReduceFunc values. The loader pins
 *     both at resolution time, so this module only validates lengths
 *     and contiguity (PyBUF_SIMPLE refuses strided exports).
 *
 * Build: `make -C native` (the _accl_combine.so target), or lazily by
 * the loader with the same flags. No numpy C API — plain buffer
 * protocol, so the .so survives numpy upgrades.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <float.h>
#include <math.h>
#include <stdint.h>
#include <string.h>

#include "bs_codec.h"

/* func codes (accl_tpu.constants.ReduceFunc) */
#define F_SUM 0
#define F_MAX 1
#define F_MIN 2
#define F_PROD 3

/* dtype codes (accl_tpu/emulator/protocol.py DTYPE_CODES) */
#define DT_F32 0
#define DT_F64 1
#define DT_I32 2
#define DT_I64 3
#define DT_F16 4
#define DT_BF16 5
#define DT_I8 6
#define DT_U8 7
#define DT_F8E4M3 8
#define DT_F8E5M2 9

/* ---- half / bfloat16 conversion (numpy/ml_dtypes round-to-nearest-even
 * parity; the float32 intermediate is exact for any two-operand sum or
 * product of 11-bit/8-bit significands, so rounding the exact result is
 * the correctly-rounded half/bf16 operation numpy produces) ---- */

static inline float half_to_float(uint16_t h) {
    uint32_t sign = (uint32_t)(h & 0x8000u) << 16;
    uint32_t exp = (h >> 10) & 0x1Fu;
    uint32_t man = h & 0x3FFu;
    uint32_t f;
    if (exp == 0) {
        if (man == 0) {
            f = sign;
        } else { /* subnormal: renormalize into f32 */
            uint32_t e = 113; /* 127 - 15 + 1 */
            while (!(man & 0x400u)) { man <<= 1; e--; }
            man &= 0x3FFu;
            f = sign | (e << 23) | (man << 13);
        }
    } else if (exp == 31) {
        f = sign | 0x7F800000u | (man << 13);
    } else {
        f = sign | ((exp + 112u) << 23) | (man << 13);
    }
    float out;
    memcpy(&out, &f, 4);
    return out;
}

static inline uint16_t float_to_half(float v) {
    uint32_t x;
    memcpy(&x, &v, 4);
    uint32_t sign = (x >> 16) & 0x8000u;
    uint32_t fexp = (x >> 23) & 0xFFu;
    uint32_t man = x & 0x7FFFFFu;
    int32_t exp = (int32_t)fexp - 127 + 15;
    if (fexp == 0xFFu) /* inf / nan */
        return (uint16_t)(sign | 0x7C00u
                          | (man ? (0x200u | (man >> 13)) : 0));
    if (exp >= 31) /* overflow -> inf */
        return (uint16_t)(sign | 0x7C00u);
    if (exp <= 0) { /* subnormal half (or zero) */
        if (exp < -10)
            return (uint16_t)sign;
        man |= 0x800000u; /* implicit bit */
        uint32_t shift = (uint32_t)(14 - exp);
        uint32_t hman = man >> shift;
        uint32_t rem = man & ((1u << shift) - 1u);
        uint32_t halfway = 1u << (shift - 1);
        if (rem > halfway || (rem == halfway && (hman & 1u)))
            hman++;
        return (uint16_t)(sign | hman);
    }
    uint32_t rem = man & 0x1FFFu;
    uint16_t out = (uint16_t)(sign | ((uint32_t)exp << 10) | (man >> 13));
    if (rem > 0x1000u || (rem == 0x1000u && (out & 1u)))
        out++;
    return out;
}

/* ---- fp8 conversion: shared with the native daemon via bs_codec.h
 * (ml_dtypes parity, pinned empirically by tests/test_combine_native.py
 * over all 256 codes + a dense f32 corpus).  The thin wrappers keep the
 * reduce bodies below readable. ---- */

static inline float e4m3_to_float(uint8_t h) { return bsc_f8_to_float(h, 3, 7, 0); }
static inline uint8_t float_to_e4m3(float v) { return bsc_float_to_f8(v, 3, 7, 0); }
static inline float e5m2_to_float(uint8_t h) { return bsc_f8_to_float(h, 2, 15, 1); }
static inline uint8_t float_to_e5m2(float v) { return bsc_float_to_f8(v, 2, 15, 1); }

static inline float bf16_to_float(uint16_t h) {
    uint32_t x = (uint32_t)h << 16;
    float f;
    memcpy(&f, &x, 4);
    return f;
}

static inline uint16_t float_to_bf16(float v) {
    uint32_t x;
    memcpy(&x, &v, 4);
    if ((x & 0x7FFFFFFFu) > 0x7F800000u) /* nan: quiet, keep payload top */
        return (uint16_t)((x >> 16) | 0x0040u);
    uint32_t lsb = (x >> 16) & 1u;
    x += 0x7FFFu + lsb; /* round to nearest even */
    return (uint16_t)(x >> 16);
}

/* numpy maximum/minimum semantics: `(a OP b || isnan(a)) ? a : b` with
 * a STRICT comparison — the second operand wins ties, which is visible
 * on signed zeros (`maximum(+0., -0.) == -0.`), and NaN in either
 * operand propagates (isnan(a) picks a; a NaN b falls through the
 * false comparison to b). */
#define FMAX_NP(a, b) (((a) > (b) || isnan(a)) ? (a) : (b))
#define FMIN_NP(a, b) (((a) < (b) || isnan(a)) ? (a) : (b))
#define IMAX_NP(a, b) (((a) >= (b)) ? (a) : (b))
#define IMIN_NP(a, b) (((a) <= (b)) ? (a) : (b))

#define LOOP(expr)                                                        \
    do {                                                                  \
        for (Py_ssize_t i = 0; i < n; i++)                                \
            o[i] = (expr);                                                \
    } while (0)

/* float/double: plain IEEE ops (identical to numpy's loops) */
#define FLOAT_BODY(T)                                                     \
    do {                                                                  \
        const T *a = (const T *)abuf;                                     \
        const T *b = (const T *)bbuf;                                     \
        T *o = (T *)obuf;                                                 \
        switch (func) {                                                   \
        case F_SUM: LOOP(a[i] + b[i]); break;                             \
        case F_PROD: LOOP(a[i] * b[i]); break;                            \
        case F_MAX: LOOP(FMAX_NP(a[i], b[i])); break;                     \
        case F_MIN: LOOP(FMIN_NP(a[i], b[i])); break;                     \
        default: return -1;                                               \
        }                                                                 \
    } while (0)

/* ints: SUM/PROD wrap via the unsigned twin (numpy wraparound parity) */
#define INT_BODY(T, U)                                                    \
    do {                                                                  \
        const T *a = (const T *)abuf;                                     \
        const T *b = (const T *)bbuf;                                     \
        T *o = (T *)obuf;                                                 \
        switch (func) {                                                   \
        case F_SUM: LOOP((T)((U)a[i] + (U)b[i])); break;                  \
        case F_PROD: LOOP((T)((U)a[i] * (U)b[i])); break;                 \
        case F_MAX: LOOP(IMAX_NP(a[i], b[i])); break;                     \
        case F_MIN: LOOP(IMIN_NP(a[i], b[i])); break;                     \
        default: return -1;                                               \
        }                                                                 \
    } while (0)

/* 16-bit floats: widen, combine in f32, round back (see header note).
 * MAXCMP/MINCMP are the comparison tokens because numpy's tie rule is
 * DTYPE-INCONSISTENT: the float16 loops (npy_half_ge) keep the FIRST
 * operand on ties (>= / <=), while ml_dtypes' bfloat16 follows the
 * f32/f64 strict rule and keeps the SECOND — visible on signed zeros
 * (`np.maximum(np.float16(+0.), np.float16(-0.))` is +0, the same call
 * on bfloat16 is -0), pinned by tests/test_combine_native.py. */
#define HALFLIKE_BODY(TO_F, FROM_F, MAXCMP, MINCMP)                       \
    do {                                                                  \
        const uint16_t *a = (const uint16_t *)abuf;                       \
        const uint16_t *b = (const uint16_t *)bbuf;                       \
        uint16_t *o = (uint16_t *)obuf;                                   \
        switch (func) {                                                   \
        case F_SUM: LOOP(FROM_F(TO_F(a[i]) + TO_F(b[i]))); break;         \
        case F_PROD: LOOP(FROM_F(TO_F(a[i]) * TO_F(b[i]))); break;        \
        case F_MAX:                                                       \
            LOOP((TO_F(a[i]) MAXCMP TO_F(b[i]) || isnan(TO_F(a[i])))      \
                     ? a[i] : b[i]);                                      \
            break;                                                        \
        case F_MIN:                                                       \
            LOOP((TO_F(a[i]) MINCMP TO_F(b[i]) || isnan(TO_F(a[i])))      \
                     ? a[i] : b[i]);                                      \
            break;                                                        \
        default: return -1;                                               \
        }                                                                 \
    } while (0)

/* fp8 quantized lanes: widen to f32, combine, round back — the exact
 * arithmetic ml_dtypes' ufunc loops run (both operands are exactly
 * representable in f32, so the f32 op is the correctly-rounded fp8 op).
 * MAX/MIN follow the ml_dtypes strict-compare rule (SECOND operand wins
 * ties — like bf16/f32, pinned on signed zeros by the test corpus).
 * NaN results carry ml_dtypes' empirically-pinned sign rule (the test
 * corpus seeds both NaN codes): add keeps the FIRST operand's NaN sign
 * and canonicalizes a second-operand NaN to the positive code; mul
 * prefers the SECOND operand's NaN sign, then the first's. NANC is the
 * canonical positive NaN code of the dtype. */
#define F8LIKE_BODY(TO_F, FROM_F, NANC)                                   \
    do {                                                                  \
        const uint8_t *a = (const uint8_t *)abuf;                         \
        const uint8_t *b = (const uint8_t *)bbuf;                         \
        uint8_t *o = (uint8_t *)obuf;                                     \
        switch (func) {                                                   \
        case F_SUM:                                                       \
            LOOP(isnan(TO_F(a[i])) ? (uint8_t)((a[i] & 0x80u) | (NANC))   \
                 : isnan(TO_F(b[i])) ? (uint8_t)(NANC)                    \
                 : FROM_F(TO_F(a[i]) + TO_F(b[i])));                      \
            break;                                                        \
        case F_PROD:                                                      \
            LOOP(isnan(TO_F(b[i])) ? (uint8_t)((b[i] & 0x80u) | (NANC))   \
                 : isnan(TO_F(a[i])) ? (uint8_t)((a[i] & 0x80u) | (NANC)) \
                 : FROM_F(TO_F(a[i]) * TO_F(b[i])));                      \
            break;                                                        \
        case F_MAX:                                                       \
            LOOP((TO_F(a[i]) > TO_F(b[i]) || isnan(TO_F(a[i])))           \
                     ? a[i] : b[i]);                                      \
            break;                                                        \
        case F_MIN:                                                       \
            LOOP((TO_F(a[i]) < TO_F(b[i]) || isnan(TO_F(a[i])))           \
                     ? a[i] : b[i]);                                      \
            break;                                                        \
        default: return -1;                                               \
        }                                                                 \
    } while (0)

static int run_reduce(int func, int dt, const void *abuf, const void *bbuf,
                      void *obuf, Py_ssize_t n) {
    switch (dt) {
    case DT_F32: FLOAT_BODY(float); return 0;
    case DT_F64: FLOAT_BODY(double); return 0;
    case DT_I32: INT_BODY(int32_t, uint32_t); return 0;
    case DT_I64: INT_BODY(int64_t, uint64_t); return 0;
    case DT_I8: INT_BODY(int8_t, uint8_t); return 0;
    case DT_U8: INT_BODY(uint8_t, uint8_t); return 0;
    case DT_F16: HALFLIKE_BODY(half_to_float, float_to_half,
                               >=, <=); return 0;
    case DT_BF16: HALFLIKE_BODY(bf16_to_float, float_to_bf16,
                                >, <); return 0;
    case DT_F8E4M3: F8LIKE_BODY(e4m3_to_float, float_to_e4m3,
                                0x7Fu); return 0;
    case DT_F8E5M2: F8LIKE_BODY(e5m2_to_float, float_to_e5m2,
                                0x7Eu); return 0;
    default: return -1;
    }
}

static const Py_ssize_t ITEMSIZE[] = {4, 8, 4, 8, 2, 2, 1, 1, 1, 1};

/* Release the GIL only past this span size: the acquire/release pair
 * costs ~100ns, which at small segments would eat the dispatch win this
 * module exists to provide. */
#define GIL_RELEASE_BYTES (1 << 14)

static PyObject *reduce_into(PyObject *self, PyObject *const *args,
                             Py_ssize_t nargs) {
    (void)self;
    if (nargs != 5) {
        PyErr_SetString(PyExc_TypeError,
                        "reduce_into(func, dtype_code, a, b, out)");
        return NULL;
    }
    int func = (int)PyLong_AsLong(args[0]);
    int dt = (int)PyLong_AsLong(args[1]);
    if ((func == -1 || dt == -1) && PyErr_Occurred())
        return NULL;
    if (dt < 0 || dt > DT_F8E5M2) {
        PyErr_SetString(PyExc_ValueError, "unsupported dtype code");
        return NULL;
    }
    Py_buffer a, b, o;
    /* PyBUF_SIMPLE demands C-contiguity — strided views fail here and
     * the Python loader falls back to numpy */
    if (PyObject_GetBuffer(args[2], &a, PyBUF_SIMPLE) < 0)
        return NULL;
    if (PyObject_GetBuffer(args[3], &b, PyBUF_SIMPLE) < 0) {
        PyBuffer_Release(&a);
        return NULL;
    }
    if (PyObject_GetBuffer(args[4], &o, PyBUF_WRITABLE) < 0) {
        PyBuffer_Release(&a);
        PyBuffer_Release(&b);
        return NULL;
    }
    Py_ssize_t isz = ITEMSIZE[dt];
    int bad = (a.len != b.len || a.len != o.len || a.len % isz != 0);
    int rc = 0;
    if (!bad) {
        Py_ssize_t n = a.len / isz;
        if (a.len >= GIL_RELEASE_BYTES) {
            Py_BEGIN_ALLOW_THREADS
            rc = run_reduce(func, dt, a.buf, b.buf, o.buf, n);
            Py_END_ALLOW_THREADS
        } else {
            rc = run_reduce(func, dt, a.buf, b.buf, o.buf, n);
        }
    }
    PyBuffer_Release(&a);
    PyBuffer_Release(&b);
    PyBuffer_Release(&o);
    if (bad) {
        PyErr_SetString(PyExc_ValueError,
                        "operand/result byte lengths disagree");
        return NULL;
    }
    if (rc) {
        PyErr_SetString(PyExc_ValueError, "unsupported func code");
        return NULL;
    }
    Py_RETURN_NONE;
}

/* ---- block-scaled quantized wire kernels (accl_tpu/quant.py) ----------
 * One f32 scale per `block` elements (absmax / qmax, clamped to a sane
 * positive-finite value), fp8/int8 payload.  The loops live in
 * bs_codec.h (shared with cclo_emud's wire lanes) with SSE2/AVX2 fast
 * paths behind a runtime dispatch; every path stays BIT-IDENTICAL to
 * the numpy reference in accl_tpu/quant.py — same single f32 roundings
 * in the same order (multiply by the reciprocal, rintf/RNE, clip,
 * cast), so serial/streamed/native-vs-numpy differentials all agree. */

static int qkind_of(int dt) {
    switch (dt) {
    case DT_I8: return BSC_QK_I8;
    case DT_F8E4M3: return BSC_QK_E4M3;
    case DT_F8E5M2: return BSC_QK_E5M2;
    default: return -1;
    }
}

static void run_bs_quantize(int qk, Py_ssize_t block, const float *x,
                            float *scales, uint8_t *q, Py_ssize_t n) {
    bsc_quantize(qk, (ptrdiff_t)block, x, scales, q, (ptrdiff_t)n);
}

static void run_bs_dequant(int qk, Py_ssize_t block, const float *scales,
                           const uint8_t *q, float *out, Py_ssize_t n) {
    bsc_dequant(qk, (ptrdiff_t)block, scales, q, out, (ptrdiff_t)n);
}

static int run_bs_combine(int func, int qk, Py_ssize_t block,
                          const float *scales, const uint8_t *q,
                          const float *other, float *out, Py_ssize_t n) {
    return bsc_combine(func, qk, (ptrdiff_t)block, scales, q, other, out,
                       (ptrdiff_t)n);
}

/* shared arg plumbing: (ints..., buffers...) with n derived from the q
 * buffer (1 byte/elem for every supported quantized dtype) */
static int bs_get_buffers(PyObject *const *args, Py_ssize_t first,
                          Py_ssize_t nbufs, Py_buffer *bufs, int writable_last) {
    for (Py_ssize_t i = 0; i < nbufs; i++) {
        int flags = (i == nbufs - 1 && writable_last) ? PyBUF_WRITABLE
                                                      : PyBUF_SIMPLE;
        if (PyObject_GetBuffer(args[first + i], &bufs[i], flags) < 0) {
            while (i--)
                PyBuffer_Release(&bufs[i]);
            return -1;
        }
    }
    return 0;
}

static PyObject *bs_quantize(PyObject *self, PyObject *const *args,
                             Py_ssize_t nargs) {
    (void)self;
    if (nargs != 5) {
        PyErr_SetString(PyExc_TypeError,
                        "bs_quantize(dtype_code, block, src, scales, q)");
        return NULL;
    }
    int qk = qkind_of((int)PyLong_AsLong(args[0]));
    Py_ssize_t block = PyLong_AsSsize_t(args[1]);
    if (PyErr_Occurred())
        return NULL;
    if (qk < 0 || block <= 0) {
        PyErr_SetString(PyExc_ValueError, "unsupported qdtype/block");
        return NULL;
    }
    Py_buffer b[3];
    /* src read-only, scales + q written: grab scales/q writable */
    if (PyObject_GetBuffer(args[2], &b[0], PyBUF_SIMPLE) < 0)
        return NULL;
    if (PyObject_GetBuffer(args[3], &b[1], PyBUF_WRITABLE) < 0) {
        PyBuffer_Release(&b[0]);
        return NULL;
    }
    if (PyObject_GetBuffer(args[4], &b[2], PyBUF_WRITABLE) < 0) {
        PyBuffer_Release(&b[0]);
        PyBuffer_Release(&b[1]);
        return NULL;
    }
    Py_ssize_t n = b[2].len;
    Py_ssize_t nb = (n + block - 1) / block;
    int bad = (b[0].len != 4 * n || b[1].len != 4 * nb);
    if (!bad) {
        if (n * 4 >= GIL_RELEASE_BYTES) {
            Py_BEGIN_ALLOW_THREADS
            run_bs_quantize(qk, block, (const float *)b[0].buf,
                            (float *)b[1].buf, (uint8_t *)b[2].buf, n);
            Py_END_ALLOW_THREADS
        } else {
            run_bs_quantize(qk, block, (const float *)b[0].buf,
                            (float *)b[1].buf, (uint8_t *)b[2].buf, n);
        }
    }
    PyBuffer_Release(&b[0]);
    PyBuffer_Release(&b[1]);
    PyBuffer_Release(&b[2]);
    if (bad) {
        PyErr_SetString(PyExc_ValueError, "buffer lengths disagree");
        return NULL;
    }
    Py_RETURN_NONE;
}

static PyObject *bs_dequant(PyObject *self, PyObject *const *args,
                            Py_ssize_t nargs) {
    (void)self;
    if (nargs != 5) {
        PyErr_SetString(PyExc_TypeError,
                        "bs_dequant(dtype_code, block, scales, q, out)");
        return NULL;
    }
    int qk = qkind_of((int)PyLong_AsLong(args[0]));
    Py_ssize_t block = PyLong_AsSsize_t(args[1]);
    if (PyErr_Occurred())
        return NULL;
    if (qk < 0 || block <= 0) {
        PyErr_SetString(PyExc_ValueError, "unsupported qdtype/block");
        return NULL;
    }
    Py_buffer b[3];
    if (bs_get_buffers(args, 2, 3, b, 1) < 0)
        return NULL;
    Py_ssize_t n = b[1].len;
    Py_ssize_t nb = (n + block - 1) / block;
    int bad = (b[0].len != 4 * nb || b[2].len != 4 * n);
    if (!bad) {
        if (n * 4 >= GIL_RELEASE_BYTES) {
            Py_BEGIN_ALLOW_THREADS
            run_bs_dequant(qk, block, (const float *)b[0].buf,
                           (const uint8_t *)b[1].buf, (float *)b[2].buf, n);
            Py_END_ALLOW_THREADS
        } else {
            run_bs_dequant(qk, block, (const float *)b[0].buf,
                           (const uint8_t *)b[1].buf, (float *)b[2].buf, n);
        }
    }
    for (int i = 0; i < 3; i++)
        PyBuffer_Release(&b[i]);
    if (bad) {
        PyErr_SetString(PyExc_ValueError, "buffer lengths disagree");
        return NULL;
    }
    Py_RETURN_NONE;
}

static PyObject *bs_combine(PyObject *self, PyObject *const *args,
                            Py_ssize_t nargs) {
    (void)self;
    if (nargs != 7) {
        PyErr_SetString(PyExc_TypeError,
                        "bs_combine(func, dtype_code, block, scales, q, "
                        "other, out)");
        return NULL;
    }
    int func = (int)PyLong_AsLong(args[0]);
    int qk = qkind_of((int)PyLong_AsLong(args[1]));
    Py_ssize_t block = PyLong_AsSsize_t(args[2]);
    if (PyErr_Occurred())
        return NULL;
    if (qk < 0 || block <= 0) {
        PyErr_SetString(PyExc_ValueError, "unsupported qdtype/block");
        return NULL;
    }
    Py_buffer b[4];
    if (bs_get_buffers(args, 3, 4, b, 1) < 0)
        return NULL;
    Py_ssize_t n = b[1].len;
    Py_ssize_t nb = (n + block - 1) / block;
    int bad = (b[0].len != 4 * nb || b[2].len != 4 * n || b[3].len != 4 * n);
    int rc = 0;
    if (!bad) {
        if (n * 4 >= GIL_RELEASE_BYTES) {
            Py_BEGIN_ALLOW_THREADS
            rc = run_bs_combine(func, qk, block, (const float *)b[0].buf,
                                (const uint8_t *)b[1].buf,
                                (const float *)b[2].buf,
                                (float *)b[3].buf, n);
            Py_END_ALLOW_THREADS
        } else {
            rc = run_bs_combine(func, qk, block, (const float *)b[0].buf,
                                (const uint8_t *)b[1].buf,
                                (const float *)b[2].buf,
                                (float *)b[3].buf, n);
        }
    }
    for (int i = 0; i < 4; i++)
        PyBuffer_Release(&b[i]);
    if (bad) {
        PyErr_SetString(PyExc_ValueError, "buffer lengths disagree");
        return NULL;
    }
    if (rc) {
        PyErr_SetString(PyExc_ValueError, "unsupported func code");
        return NULL;
    }
    Py_RETURN_NONE;
}

/* ---- codec dispatch introspection: the bit-identity tests drive both
 * the vectorized and the scalar path in-process through these (no
 * subprocess/env round trip), and the benchmarks label their ladders
 * with the level actually measured. ---- */

static PyObject *codec_level(PyObject *self, PyObject *args) {
    (void)self;
    (void)args;
    return PyLong_FromLong(bsc_level());
}

static PyObject *codec_set_level(PyObject *self, PyObject *const *args,
                                 Py_ssize_t nargs) {
    (void)self;
    if (nargs != 1) {
        PyErr_SetString(PyExc_TypeError, "codec_set_level(level)");
        return NULL;
    }
    int lvl = (int)PyLong_AsLong(args[0]);
    if (lvl == -1 && PyErr_Occurred())
        return NULL;
    return PyLong_FromLong(bsc_set_level(lvl));
}

static PyMethodDef methods[] = {
    {"reduce_into", (PyCFunction)(void (*)(void))reduce_into,
     METH_FASTCALL,
     "reduce_into(func, dtype_code, a, b, out): out[i] = func(a[i], b[i]) "
     "over contiguous same-length buffers; bit-identical to the numpy "
     "ufunc for every supported (func, dtype)."},
    {"bs_quantize", (PyCFunction)(void (*)(void))bs_quantize,
     METH_FASTCALL,
     "bs_quantize(dtype_code, block, src_f32, scales_f32, q_out): "
     "per-block absmax scales + quantized payload (accl_tpu/quant.py "
     "reference parity)."},
    {"bs_dequant", (PyCFunction)(void (*)(void))bs_dequant,
     METH_FASTCALL,
     "bs_dequant(dtype_code, block, scales_f32, q, out_f32): "
     "out[i] = decode(q[i]) * scales[i/block]."},
    {"bs_combine", (PyCFunction)(void (*)(void))bs_combine,
     METH_FASTCALL,
     "bs_combine(func, dtype_code, block, scales_f32, q, other_f32, "
     "out_f32): fused dequant+combine — out[i] = func(other[i], "
     "decode(q[i]) * scales[i/block]) with f32 accumulation."},
    {"codec_level", (PyCFunction)codec_level, METH_NOARGS,
     "codec_level(): active block-scale codec dispatch level "
     "(0=scalar, 1=SSE2, 2=AVX2)."},
    {"codec_set_level", (PyCFunction)(void (*)(void))codec_set_level,
     METH_FASTCALL,
     "codec_set_level(level): force the codec dispatch level (clamped "
     "to host support); returns the level in effect."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "_accl_combine",
    "Compiled contiguous-span combine kernels for the emulator dataplane.",
    -1, methods, NULL, NULL, NULL, NULL,
};

PyMODINIT_FUNC PyInit__accl_combine(void) {
    /* resolve the SIMD dispatch level and build the decode LUTs while
     * still single-threaded (import lock held) */
    bsc_init();
    return PyModule_Create(&module);
}
