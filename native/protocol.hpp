// Shared wire-protocol definitions for accl_tpu native components.
//
// Must match accl_tpu/emulator/protocol.py: length-prefixed (u32-LE)
// binary frames over TCP; body = u8 message type + payload. Used by the
// rank daemon (cclo_emud.cpp) and the C++ host driver (accl_driver.hpp)
// — the C++ analog of the reference's split between the device-side ZMQ
// bridge (test/zmq/zmq_intf.cpp) and the XRT host driver (driver/xrt/).

#pragma once

#include <netinet/in.h>
#include <sys/socket.h>

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace accl_proto {

enum Msg : uint8_t {
  MSG_CALL = 1, MSG_WAIT = 2, MSG_ALLOC = 3, MSG_FREE = 4,
  MSG_WRITE_MEM = 5, MSG_READ_MEM = 6, MSG_CONFIG_COMM = 7,
  MSG_SET_TIMEOUT = 8, MSG_SET_SEG = 9, MSG_PING = 10, MSG_SHUTDOWN = 11,
  MSG_RESET = 12, MSG_DUMP_RX = 13, MSG_GET_INFO = 14,
  MSG_STREAM_PUSH = 15, MSG_STREAM_POP = 16,
  MSG_STATUS = 100, MSG_CALL_ID = 101, MSG_DATA = 102,
  MSG_ETH = 50,
};

static const uint32_t STATUS_PENDING = 0xFFFFFFFFu;

// eth-frame strm lane codes (emulator/protocol.py): 0 = pool-destined
// data, 1 = stream-port delivery, >= 2 are control lanes. The native
// daemon speaks the retransmission ACK lane; the remaining control
// lanes (heartbeat / RMA / join) stay python-tier features and are
// ignored on ingest.
enum Strm : uint8_t {
  ACK_STRM = 2,       // retransmission acknowledgement (pack_ack payload)
  HB_STRM = 3,        // membership heartbeat
  RMA_STRM = 4,       // one-sided RMA control
  RMA_DATA_STRM = 5,  // one-sided RMA payload segments
  JOIN_STRM = 6,      // membership join poll
};

// capability bits advertised in the MSG_GET_INFO caps word (keep in sync
// with protocol.py CAP_*). This daemon advertises CAP_RETX_ACK (UDP
// selective-retransmission responder) and, when payload checksums are
// enabled ($ACCL_TPU_CSUM, default on), CAP_CSUM | CAP_CSUM_C (trailing
// crc32c integrity word). CAP_RMA and CAP_SHM stay clear: the one-sided
// RMA engine and the shared-memory dataplane remain python-tier lanes.
enum Cap : uint32_t {
  CAP_RETX_ACK = 1,
  CAP_RMA = 2,
  CAP_CSUM = 4,
  CAP_CSUM_C = 8,
  CAP_SHM = 16,
};

// -- payload checksums (crc32c, Castagnoli) ---------------------------------
// Must produce the SAME value as the python tiers' google-crc32c binding
// (protocol.py csum_of): reflected polynomial 0x82F63B78, init and final
// xor 0xFFFFFFFF. Hardware SSE4.2 path when the host has it (the same
// instruction google-crc32c uses), software table otherwise — both
// variants are bit-identical, so CAP_CSUM_C is always truthful.

inline const uint32_t* crc32c_table() {
  static uint32_t table[256];
  static bool init = [] {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c >> 1) ^ (0x82F63B78u & (~(c & 1) + 1));
      table[i] = c;
    }
    return true;
  }();
  (void)init;
  return table;
}

#if defined(__x86_64__) && (defined(__GNUC__) || defined(__clang__))
__attribute__((target("sse4.2")))
inline uint32_t crc32c_hw(uint32_t crc, const uint8_t* p, size_t n) {
  uint64_t c = crc;
  while (n >= 8) {
    uint64_t v;
    std::memcpy(&v, p, 8);
    c = __builtin_ia32_crc32di(c, v);
    p += 8;
    n -= 8;
  }
  uint32_t c32 = static_cast<uint32_t>(c);
  while (n--) c32 = __builtin_ia32_crc32qi(c32, *p++);
  return c32;
}

inline bool crc32c_have_hw() {
  static const bool have = __builtin_cpu_supports("sse4.2");
  return have;
}
#else
inline bool crc32c_have_hw() { return false; }
#endif

inline uint32_t crc32c(const uint8_t* p, size_t n) {
  uint32_t crc = 0xFFFFFFFFu;
#if defined(__x86_64__) && (defined(__GNUC__) || defined(__clang__))
  if (crc32c_have_hw()) return crc32c_hw(crc, p, n) ^ 0xFFFFFFFFu;
#endif
  const uint32_t* table = crc32c_table();
  for (size_t i = 0; i < n; ++i)
    crc = table[(crc ^ p[i]) & 0xFF] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

// $ACCL_TPU_CSUM: default on; "0"/""/"false"/"off" disable (the python
// tiers' csum_enabled_from_env twin — read once at fabric construction)
inline bool csum_enabled_from_env() {
  const char* v = std::getenv("ACCL_TPU_CSUM");
  if (!v) return true;
  std::string s(v);
  return !(s == "0" || s.empty() || s == "false" || s == "off");
}

// $ACCL_TPU_RETX_WINDOW: in-flight frames per (dst, comm) channel; 0
// disables retransmission (python reliability.retx_window_from_env twin)
static const int DEFAULT_RETX_WINDOW = 64;
inline int retx_window_from_env() {
  const char* v = std::getenv("ACCL_TPU_RETX_WINDOW");
  if (!v || !*v) return DEFAULT_RETX_WINDOW;
  int w = std::atoi(v);
  return w < 0 ? 0 : w;
}

// -- retransmission ACK payload (rides strm=ACK_STRM eth frames) ------------
// cumulative frontier u32, selective count u16, then each out-of-order
// received seqn u32 (protocol.py pack_ack/unpack_ack). comm_id rides the
// envelope; the cumulative value is mirrored in the envelope seqn.
inline std::vector<uint8_t> pack_ack(uint32_t cum,
                                     const std::vector<uint32_t>& sel) {
  std::vector<uint8_t> out;
  out.reserve(6 + 4 * sel.size());
  out.resize(6);
  std::memcpy(out.data(), &cum, 4);
  uint16_t n = static_cast<uint16_t>(sel.size());
  std::memcpy(out.data() + 4, &n, 2);
  for (uint32_t s : sel) {
    size_t off = out.size();
    out.resize(off + 4);
    std::memcpy(out.data() + off, &s, 4);
  }
  return out;
}

inline bool unpack_ack(const uint8_t* p, size_t len, uint32_t* cum,
                       std::vector<uint32_t>* sel) {
  if (len < 6) return false;
  std::memcpy(cum, p, 4);
  uint16_t n;
  std::memcpy(&n, p + 4, 2);
  if (len < 6 + 4u * n) return false;
  sel->resize(n);
  for (uint16_t i = 0; i < n; ++i)
    std::memcpy(&(*sel)[i], p + 6 + 4 * i, 4);
  return true;
}

// shared daemon resource bounds (keep in sync with protocol.py); the
// allocation ceiling stays below the frame cap so every allocatable
// buffer round-trips one MSG_WRITE_MEM / MSG_READ_MEM frame.  2 GiB is
// the largest power of two whose frame (payload + 64-byte header slack)
// still fits the u32 length word; larger than 2 GiB stays
// rejected (the size checks are strict >)
static const uint64_t MAX_CALL_BYTES = 1ull << 40;
static const uint64_t MAX_ALLOC_BYTES = 1ull << 31;

enum Op : uint8_t {
  OP_CONFIG = 0, OP_COPY = 1, OP_COMBINE = 2, OP_SEND = 3, OP_RECV = 4,
  OP_BCAST = 5, OP_SCATTER = 6, OP_GATHER = 7, OP_REDUCE = 8,
  OP_ALLGATHER = 9, OP_ALLREDUCE = 10, OP_REDUCE_SCATTER = 11,
  OP_BARRIER = 12, OP_ALLTOALL = 13, OP_PUT = 14, OP_GET = 15,
  // variable-count all-to-all: per-peer count vectors ride an optional
  // trailing record on the MSG_CALL frame (protocol.py pack_call). This
  // daemon has no vector-exchange expansion — it rejects the opcode
  // typed (E_NOT_IMPLEMENTED, with the feature NAME in the status-reply
  // payload so the python driver can surface it) rather than running a
  // fixed-count program the peers would mismatch.
  OP_ALLTOALLV = 16, OP_NOP = 255,
};

enum Func : uint8_t { FN_SUM = 0, FN_MAX = 1, FN_MIN = 2, FN_PROD = 3 };

// config-call subfunctions, carried in the descriptor's tag with the value
// in count (CfgFunc in accl_tpu/constants.py; reference CCLOCfgFunc,
// driver/pynq/accl.py:179-187 <-> ccl_offload_control.c:1240-1283)
enum Cfg : uint8_t {
  CFG_RESET = 0, CFG_ENABLE_PKT = 1, CFG_SET_TIMEOUT = 2,
  CFG_OPEN_PORT = 3, CFG_OPEN_CON = 4, CFG_SET_STACK = 5,
  CFG_SET_SEG = 6, CFG_CLOSE_CON = 7, CFG_START_PROF = 8,
  CFG_END_PROF = 9,
};

enum CompFlag : uint8_t {
  C_NONE = 0, C_OP0 = 1, C_OP1 = 2, C_RES = 4, C_ETH = 8,
  // block-scaled quantized wire (accl_tpu/quant.py): per-block f32 scale
  // headers ahead of the fp8/int8 payload. The daemon executes this lane
  // natively via the bs_codec twins (bsc_quantize/bsc_dequant), emitting
  // and parsing the same packed segment layout as the python tiers
  // ([0xB5 | qcode | block u16 | count u32 | scales | q]).
  C_BLOCK_SCALED = 16,
};

// per-call collective algorithm selector (CollectiveAlgorithm in
// accl_tpu/constants.py; the reference's sw/ring/rr variant axis,
// driver/xrt/include/xlnx-consts.hpp:43-66)
enum Alg : uint8_t {
  ALG_AUTO = 0, ALG_RING = 1, ALG_ROUND_ROBIN = 2, ALG_TREE = 3,
  ALG_FUSED_RING = 4, ALG_NON_FUSED = 5,
};

enum Err : uint32_t {
  E_OK = 0,
  E_DMA_MISMATCH = 1u << 0,
  E_COMPRESSION = 1u << 5,
  E_KRNL_TIMEOUT = 1u << 6,
  E_RECV_TIMEOUT = 1u << 8,
  E_DMA_SIZE = 1u << 12,
  E_OPEN_PORT = 1u << 13,
  E_OPEN_CON = 1u << 14,
  E_COMM_NOT_CONFIGURED = 1u << 15,
  // scenario valid on other tiers but not implemented by this daemon
  // (ErrorCode.COLLECTIVE_NOT_IMPLEMENTED in constants.py) — distinct
  // from E_INVALID so a capability gap is diagnosable from the word
  E_NOT_IMPLEMENTED = 1u << 19,
  E_SPARE_OVERFLOW = 1u << 20,
  E_INVALID = 1u << 23,
  // a deferred MSG_WAIT for an id so old that both its status and (if
  // it failed) its failed-calls record aged out: retired, outcome
  // unknowable (ErrorCode.CALL_OUTCOME_UNKNOWN in constants.py)
  E_OUTCOME_UNKNOWN = 1u << 24,
};

static const uint32_t TAG_ANY = 0xFFFFFFFFu;

// dtype codes match protocol.py DTYPE_CODES
enum DType : uint8_t {
  DT_F32 = 0, DT_F64 = 1, DT_I32 = 2, DT_I64 = 3,
  DT_F16 = 4, DT_BF16 = 5, DT_I8 = 6, DT_U8 = 7,
  DT_F8E4M3 = 8, DT_F8E5M2 = 9,  // quantized wire lanes (ml_dtypes twins)
};

inline size_t dtype_size(uint8_t dt) {
  switch (dt) {
    case DT_F32: case DT_I32: return 4;
    case DT_F64: case DT_I64: return 8;
    case DT_F16: case DT_BF16: return 2;
    default: return 1;  // i8/u8/fp8
  }
}

// -- framing ---------------------------------------------------------------

inline bool recv_exact(int fd, void* buf, size_t n) {
  uint8_t* p = static_cast<uint8_t*>(buf);
  while (n) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

inline bool send_exact(int fd, const void* buf, size_t n) {
  const uint8_t* p = static_cast<const uint8_t*>(buf);
  while (n) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

// The largest legitimate frame is a device-memory write of one maximal
// (MAX_ALLOC_BYTES) buffer plus the message header.  The length header is
// attacker-controlled: beyond the cap the connection is dropped before
// any allocation is committed, and an allocation failure below the cap
// drops the connection rather than letting bad_alloc escape the serving
// thread.
constexpr uint32_t MAX_FRAME_LEN =
    static_cast<uint32_t>(MAX_ALLOC_BYTES) + 64;

inline bool recv_frame(int fd, std::vector<uint8_t>& body) {
  uint32_t len;
  if (!recv_exact(fd, &len, 4)) return false;
  if (len > MAX_FRAME_LEN) return false;
  try {
    body.resize(len);
  } catch (const std::bad_alloc&) {
    return false;
  }
  return len == 0 || recv_exact(fd, body.data(), len);
}

// Pipelined batch: every frame's length prefix + body coalesce into one
// buffer and one send (the Python side's P.send_frames). Framing is
// byte-identical to send_frame/recv_frame — same native-order uint32
// prefix — because this is the ONLY other place frames are written.
inline bool send_frames(int fd, const std::vector<std::vector<uint8_t>>& bodies) {
  size_t total = 0;
  for (const auto& b : bodies) total += 4 + b.size();
  std::vector<uint8_t> wire;
  wire.reserve(total);
  for (const auto& b : bodies) {
    uint32_t len = static_cast<uint32_t>(b.size());
    const uint8_t* lp = reinterpret_cast<const uint8_t*>(&len);
    wire.insert(wire.end(), lp, lp + 4);
    wire.insert(wire.end(), b.begin(), b.end());
  }
  size_t sent = 0;
  while (sent < wire.size()) {
    ssize_t r = ::send(fd, wire.data() + sent, wire.size() - sent,
                       MSG_NOSIGNAL);
    if (r <= 0) return false;
    sent += static_cast<size_t>(r);
  }
  return true;
}

inline bool send_frame(int fd, const std::vector<uint8_t>& body) {
  // scatter-gather send: the length header and the body go out in one
  // syscall without copying the body into a fresh buffer (a per-frame
  // MiB-scale memcpy at large messages otherwise)
  uint32_t len = static_cast<uint32_t>(body.size());
  struct iovec iov[2];
  iov[0].iov_base = &len;
  iov[0].iov_len = 4;
  iov[1].iov_base = const_cast<uint8_t*>(body.data());
  iov[1].iov_len = body.size();
  struct msghdr msg = {};
  msg.msg_iov = iov;
  msg.msg_iovlen = body.empty() ? 1 : 2;
  size_t sent = 0, total = 4 + body.size();
  while (sent < total) {
    ssize_t r = ::sendmsg(fd, &msg, MSG_NOSIGNAL);
    if (r <= 0) return false;
    sent += static_cast<size_t>(r);
    // advance the iovecs past what went out (short writes happen under
    // backpressure)
    size_t done = static_cast<size_t>(r);
    for (int i = 0; i < 2 && done; ++i) {
      size_t take = done < iov[i].iov_len ? done : iov[i].iov_len;
      iov[i].iov_base = static_cast<uint8_t*>(iov[i].iov_base) + take;
      iov[i].iov_len -= take;
      done -= take;
    }
    msg.msg_iov = iov[0].iov_len ? iov : iov + 1;
    msg.msg_iovlen = (iov[0].iov_len ? 1 : 0) + (iov[1].iov_len ? 1 : 0);
  }
  return true;
}

template <typename T>
inline T get_le(const uint8_t* p) {
  T v;
  std::memcpy(&v, p, sizeof(T));
  return v;
}

template <typename T>
inline void put_le(std::vector<uint8_t>& out, T v) {
  size_t off = out.size();
  out.resize(off + sizeof(T));
  std::memcpy(out.data() + off, &v, sizeof(T));
}

inline std::vector<uint8_t> status_reply(uint32_t err) {
  std::vector<uint8_t> r{MSG_STATUS};
  put_le<uint32_t>(r, err);
  return r;
}

// typed reject with the unsupported feature's NAME riding after the
// error word — old drivers slice reply[1:5] and never see it; the
// python driver decodes reply[5:] into the raised ACCLError's context
inline std::vector<uint8_t> status_reply(uint32_t err, const char* feature) {
  std::vector<uint8_t> r = status_reply(err);
  if (feature && *feature)
    r.insert(r.end(), feature, feature + std::strlen(feature));
  return r;
}

}  // namespace accl_proto
